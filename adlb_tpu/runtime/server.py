"""Server reactor.

Equivalent of the reference's ~2,100-line single-threaded server event loop
(``ADLBP_Server``, reference ``src/adlb.c:382-2506``): poll the transport,
dispatch by tag, run periodic duties (state sync, push-trigger, exhaustion
check, watchdog logging). Re-architected around indexed queues
(:mod:`adlb_tpu.runtime.queues`) and two interchangeable cross-server
balancing strategies:

* **steal** — faithful-in-spirit rebuild of the reference heuristics:
  per-server state broadcast (replacing the 0.1 s qmstat ring pass,
  reference ``src/adlb.c:806-822,1705-1757``), pull-side RFR work stealing
  with stale-state patching and UNRESERVE race compensation (reference
  ``src/adlb.c:1802-2070``), and memory-pressure pushes with PUSH_DEL
  cancellation (reference ``src/adlb.c:509-556,2109-2362``).
* **tpu** — the reference's gossip+greedy matching is replaced by a periodic
  batched global assignment solve: servers stream fixed-shape queue-state
  snapshots to the balancer (the master server), a jitted JAX solve computes
  task->requester placement, and plan entries are enacted through the same
  pin/forward/UNRESERVE discipline so plan staleness is harmless (plan
  entries are hints validated against live state, like the reference's
  PUSH_QUERY_RESP validation, ``src/adlb.c:2182-2192``).

Termination protocols (explicit no-more-work, double-pass exhaustion
detection, held two-phase shutdown) follow the reference's ring-token designs
(reference ``src/adlb.c:754-785,1385-1801``) over the server ring.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from adlb_tpu.obs import profile
from adlb_tpu.obs.flight import FlightRecorder
from adlb_tpu.obs.journey import TAIL_MIN_COUNT, JourneyRecorder, trace_fields
from adlb_tpu.obs.metrics import Registry, attach, quantile_of
from adlb_tpu.runtime.debug import aprintf, self_diagnosis
from adlb_tpu.runtime.hedge import HedgeManager, should_hedge
from adlb_tpu.runtime.messages import Msg, Tag, msg
from adlb_tpu.runtime.trace import PID_SERVER, Tracer
from adlb_tpu.runtime.queues import (
    CommonStore,
    LeaseTable,
    MemoryAccountant,
    PartitionedWorkQueue,
    ReserveQueue,
    RqEntry,
    TargetedDirectory,
    WorkQueue,
    WorkUnit,
)
from adlb_tpu.runtime.transport import Endpoint
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.types import (
    ADLB_BACKOFF,
    ADLB_DONE_BY_EXHAUSTION,
    ADLB_ERROR,
    ADLB_FENCED,
    ADLB_LOWEST_PRIO,
    ADLB_NO_CURRENT_WORK,
    ADLB_NO_MORE_WORK,
    ADLB_PUT_REJECTED,
    ADLB_RETRY,
    ADLB_SUCCESS,
    AdlbError,
    InfoKey,
    WorkHandle,
)


class _BalancerWorker(threading.Thread):
    """The balancer brain, off the reactor thread.

    The solve's device round-trip (notably over a remote-TPU tunnel, where
    dispatch is milliseconds and first compile is tens of seconds) must never
    block the master's protocol loop, so the master only *updates snapshots*
    and wakes this thread; the thread coalesces to the latest state, solves,
    and sends SS_PLAN_MATCH messages itself (endpoint sends are
    thread-safe). Plan staleness this introduces is already handled by
    enactment-time validation.

    Re-planning storms are suppressed by remembering when each requester/task
    was last planned: both stay ineligible until a *fresh* snapshot (stamp
    newer than the plan) shows them still parked/queued.
    """

    def __init__(self, server: "Server") -> None:
        super().__init__(daemon=True, name=f"adlb-balancer-{server.rank}")
        self.server = server
        self.wake = threading.Event()
        self.stopped = False

    def stop(self) -> None:
        self.stopped = True
        self.wake.set()

    def run(self) -> None:
        s = self.server
        from adlb_tpu.balancer.engine import PlanEngine

        engine = PlanEngine(
            types=s.world.types,
            max_tasks=s.cfg.balancer_max_tasks,
            max_requesters=s.cfg.balancer_max_requesters,
            backend=s.cfg.solver_backend,
            max_malloc_per_server=s.cfg.max_malloc_per_server,
            use_mesh=s.cfg.balancer_mesh == "auto",
            nservers=s.world.nservers,
            host_threshold_reqs=s.cfg.solver_host_threshold,
            lookahead=s.cfg.balancer_lookahead,
            look_max=s.cfg.balancer_look_max,
            grow_window=s.cfg.balancer_grow_window,
            inflow_ttl=s.cfg.balancer_inflow_ttl,
            inflow_min_age=s.cfg.balancer_inflow_min_age,
            host_ledger=s.cfg.host_ledger,
            auction=s.cfg.balancer_auction,
            metrics=s.metrics,
            max_jobs=s.cfg.balancer_max_jobs,
            job_weights=s.cfg.job_weights,
        )
        s._solver = engine.solver
        s._engine = engine  # weights fan-out (set_job_weights) target
        from adlb_tpu.obs import profile as _profile

        _profile.register_thread("balancer")
        prof = _profile.active()
        # Event-gated loop: sleep on the doorbell (armed by parks, task
        # deltas, qmstat/hungry changes and failover patches) and fall
        # back to a slow insurance tick — an idle world runs ~4 rounds/s
        # instead of spinning through wake/solve cycles, and the sampler
        # attributes waiting to "balancer_idle" so the parity profile's
        # balancer_tick share measures ROUNDS, not thread lifetime.
        idle = s.cfg.balancer_idle_interval
        while True:
            if prof is not None:
                prof.set_phase("balancer_idle")
            self.wake.wait(timeout=idle if idle > 0 else None)
            self.wake.clear()
            if self.stopped or s.done:
                return
            try:
                if prof is not None:
                    prof.set_phase("balancer_tick")
                gap, produced = self._one_round(engine)
                if prof is not None:
                    prof.set_phase("balancer_idle")
                if gap > 0:
                    time.sleep(gap)
                if produced:
                    # a plan-bearing round usually uncovers follow-on
                    # work (the drained holder's next snapshot may lag
                    # the insurance tick); re-arm so the next round runs
                    # right after the rate-limit gap
                    self.wake.set()
            except Exception as e:  # noqa: BLE001
                # The balancer must survive solver/backend errors — in tpu
                # mode there is no other cross-server matching mechanism.
                # Force the numpy host path (no accelerator involvement)
                # and keep going.
                import sys as _sys

                print(
                    f"[adlb balancer] solve failed ({e!r}); forcing host "
                    f"solve path and retrying",
                    file=_sys.stderr,
                )
                engine.force_host_path()
                time.sleep(0.05)

    def _one_round(self, engine) -> tuple:
        s = self.server
        # live fair-share weight change (POST /jobs/<id> or controller):
        # applied here, not on the reactor — solver caches are this
        # thread's to flush. dict.pop is atomic, so a concurrent set
        # either lands now or wakes the next round.
        pw = s.__dict__.pop("_pending_job_weights", None)
        if pw is not None:
            engine.set_job_weights(pw)
        snaps = s._snapshots.fork()  # one copy: the round AND the fetch
        # lookup below must see the same view, or a reactor-thread
        # snapshot swap mid-round could silently drop a match's flag.
        # fork() carries the store's version marks so the ledger's sync
        # only touches ranks that changed since the previous round
        if s.tracer is not None:
            with s.tracer.span("balancer:round"):
                matches, migrations = engine.round(snaps, s.world)
        else:
            matches, migrations = engine.round(snaps, s.world)
        if matches:
            # whether each planned requester's park is a fused reserve
            # (get_work/stream): snapshot req tuples carry it as a 4th
            # element (3-tuples from native planes default to False), and
            # the holder uses it to ship the payload in the RFR response
            # instead of a handle (remote fused fetch)
            fetch_by_req: dict[tuple, bool] = {}
            for src, snap in snaps.items():
                for r in snap.get("reqs") or ():
                    fetch_by_req[(src, r[0], r[1])] = (
                        bool(r[3]) if len(r) > 3 else False
                    )
        dead = s._dead_servers
        for holder, seqno, req_home, for_rank, rqseqno in matches:
            if holder in dead or req_home in dead:
                continue  # racing failover: the next round re-plans
            try:
                s.ep.send(
                    holder,
                    msg(
                        Tag.SS_PLAN_MATCH,
                        s.rank,
                        seqno=seqno,
                        for_rank=for_rank,
                        req_home=req_home,
                        rqseqno=rqseqno,
                        fetch=int(
                            fetch_by_req.get(
                                (req_home, for_rank, rqseqno), False
                            )
                        ),
                    ),
                )
            except OSError:
                continue  # the reactor's own evidence declares the death
        for src_rank, dest, seqnos, mig_id in migrations:
            if src_rank in dead or dest in dead:
                continue
            try:
                s.ep.send(
                    src_rank,
                    msg(Tag.SS_PLAN_MIGRATE, s.rank, dest=dest, seqnos=seqnos,
                        mig_id=mig_id),
                )
            except OSError:
                continue
        gap = 0.0
        if s.cfg.balancer_min_gap > 0:
            # module already cached by run()'s deferred import; this stays
            # a plain lookup, not a fresh module load
            from adlb_tpu.balancer.engine import round_gap

            gap = round_gap(s.cfg.balancer_min_gap, matches, migrations)
        # the caller sleeps the gap (under the idle phase marker) and
        # re-arms the doorbell after plan-bearing rounds
        return gap, bool(matches or migrations)


class _PeerState:
    """What this server believes about a peer — the reference's qmstat entry
    {nbytes_used, qlen_unpin_untarg, type_hi_prio[]} (reference
    ``src/adlb.c:151-159``)."""

    def __init__(self) -> None:
        self.nbytes = 0
        self.qlen = 0
        self.hi_prio: dict[int, int] = {}
        # per-job inventory cells {(job, type): prio} — present only
        # while non-default namespaces hold work (service mode)
        self.job_hi: dict[tuple[int, int], int] = {}
        self.rss_kb = 0
        self.stamp = 0.0


class Server:
    def __init__(
        self, world: WorldSpec, cfg: Config, ep: Endpoint, abort_event=None
    ) -> None:
        from adlb_tpu.runtime.membership import MemberView

        # every server holds the DYNAMIC membership view (behavior-
        # identical to the plain spec until membership actually changes);
        # scale-out shards arrive with a pre-seeded view
        world = MemberView.of(world)
        self.world = world
        self.cfg = cfg
        self.ep = ep
        self.rank = ep.rank
        self.is_master = self.rank == world.master_server_rank
        self.local_apps = set(world.local_apps(self.rank))

        # per-job wq partitions behind the single-queue surface: job 0
        # keeps the configured implementation (incl. the C++ core);
        # non-default namespaces get lazy pure-Python partitions
        self.wq = PartitionedWorkQueue(lambda: self._make_wq(cfg))
        self.rq = ReserveQueue()
        self.tq = TargetedDirectory()
        self.mem = MemoryAccountant(
            cfg.max_malloc_per_server,
            soft_frac=cfg.mem_soft_frac,
            hard_frac=cfg.mem_hard_frac,
        )
        self.cq = CommonStore(on_gc=self._on_common_gc)
        # disk spill tier (Config(spill_dir), runtime/spill.py): cold
        # parked payloads move to disk above the spill watermark and
        # fault back in at delivery time — see _maybe_spill/_unspill
        self.spill = None
        if cfg.spill_dir is not None:
            from adlb_tpu.runtime.spill import SpillStore

            self.spill = SpillStore(cfg.spill_dir, self.rank)
        # lease per pinned unit (owner rank, lease id, grant time): under
        # on_worker_failure="reclaim" a dead owner's leases turn back into
        # queued work instead of blocking exhaustion forever
        self.leases = LeaseTable()

        # ---- gray-failure state (Config(lease_timeout_s) / quarantine) ----
        # liveness clock per app rank: stamped by EVERY frame the rank
        # sends here (protocol traffic piggybacks liveness) plus its
        # periodic FA_HEARTBEAT; the lease-expiry scan ages a lease from
        # max(grant, renewal, owner last-heard), and the HOME server
        # declares a rank hung after 2x the timeout of total silence —
        # the bounded detection a SIGSTOP'd (gray-failed) worker needs,
        # since it never EOFs
        self._lease_armed = cfg.lease_timeout_s > 0
        self._last_heard: dict[int, float] = {}
        # fencing tokens from expired leases: (seqno, owner) pairs whose
        # lease EXPIRED — the unit re-enqueued under a fresh attempt, and
        # any late Get_reserved from the old owner answers ADLB_FENCED so
        # a slow-but-alive worker can never double-settle it. Bounded
        # like the failover tombstones.
        self._fences: set[tuple[int, int]] = set()
        self._fence_order: deque = deque()
        # fences adopted from a failed-over predecessor, keyed by ITS
        # numbering (the fenced owner's rerouted fetch arrives stamped
        # fo_from): fencing must survive failover or a takeover would
        # quietly un-fence a stalled owner
        self._adopted_fences: set[tuple[int, int, int]] = set()
        # dead-letter quarantine: units whose failure-attempt count
        # exceeded Config(max_unit_retries) — out of the wq (settled for
        # exhaustion voting), counted exactly-once, retrievable via
        # ctx.get_quarantined() / ops /deadletter
        self.quarantine: list[dict] = []

        # ---- server failover (Config(on_server_failure="failover")) ----
        # Each server streams a replication log of its pool mutations to
        # its ring-successor buddy (adlb_tpu/runtime/replica.py) and
        # passively mirrors its ring predecessor's stream; on a server's
        # death the survivors prune it and the buddy replays the mirror
        # into its own queues, taking over home-server duty.
        self._failover = (
            cfg.on_server_failure == "failover" and world.nservers > 1
        )
        self._dead_servers: set[int] = set()
        self._srv_route: dict[int, int] = {}  # dead server -> its buddy
        self.repl = None  # ReplicationLog toward the current buddy
        # primary rank -> ReplicaMirror (normally just the ring
        # predecessor; re-bootstraps after intermediate deaths can add
        # more — see _rebootstrap_repl)
        self.mirrors: dict[int, "object"] = {}
        if self._failover:
            from adlb_tpu.runtime import replica

            self.repl = replica.ReplicationLog(world.ring_next(self.rank))
        # ---- master failover (the brain survives its own death) ----
        # The master's ring buddy is the standing DEPUTY: the master's
        # durable control-plane state (membership/epoch/watermark, live
        # SLO objectives, controller policy, parked scale requests, job
        # weights) rides the SAME replication stream as the pool shard,
        # so a promoted deputy is a fully functioning brain. Succession
        # fans SS_MASTER_TAKEOVER behind an ack barrier (same shape as
        # the membership barrier): exhaustion/END verdicts defer while
        # it is open, so no termination verdict races the new epoch.
        # Plain attrs only — an unconfigured world mints no counters.
        self._takeover_tok = 0
        self._takeover_pending: Optional[dict] = None

        # ---- durable service mode (Config(wal_dir), runtime/wal.py) ----
        # the replica op stream teed to an append-only on-disk log with
        # group-commit fsync; put acks are held for the commit that
        # makes their entries durable (write-ahead across process death)
        self.wal = None
        if cfg.wal_dir:
            from adlb_tpu.runtime import wal as walmod

            self.wal = walmod.WriteAheadLog(
                cfg.wal_dir, self.rank, world,
                fsync_ms=cfg.wal_fsync_ms,
                max_bytes=cfg.wal_max_bytes,
                allow_legacy=cfg.allow_legacy_shards,
            )
        # the ONE mutation-log handle every pool-state change goes
        # through: the network replication log, the WAL, or a tee of
        # both (None when neither is armed)
        self._refresh_wlog()

        # ---- job namespaces (service mode, runtime/jobs.py) ----
        from adlb_tpu.runtime.jobs import JobTable

        self.jobs = JobTable()
        # which namespace each LOCAL app rank is attached to (updated by
        # FA_JOB_CTL attach and by any reserve naming a job): the
        # per-job exhaustion vote reads it for this server's local apps
        self._rank_job: dict[int, int] = {}
        self._job_next_id = 1  # master-allocated job ids
        # control-plane injection from the ops HTTP thread (POST /jobs):
        # the reactor drains this on its periodic pass (see ctl_request)
        self._ctl_inbox: deque = deque()
        # units dropped by a job kill: their outstanding handles answer
        # ADLB_NO_MORE_WORK instead of crashing the reactor (bounded,
        # like fences)
        self._killed_units: set[int] = set()
        self._killed_order: deque = deque()
        self.wal_recovered = 0  # units adopted from the WAL at startup

        # ---- elastic membership (adlb_tpu/runtime/membership.py) ----
        # master's id pool for attached ranks / scale-out servers: above
        # the base world AND the sidecar pseudo-rank (== spec.nranks)
        self._member_next_rank = world.spec.nranks + 1
        # fan-out/ack barrier: the master answers an attach/detach only
        # once every live server acked the membership change, so a new
        # rank's first frame can never outrun its own membership
        self._member_tok = 0
        self._member_pending: dict[int, dict] = {}
        # scale-out shards whose reactors announced "ready" (master);
        # shards published live fleet-wide (server_live fan-out) — only
        # these join rings, fan-outs, and buddy walks
        self._member_ready: set[int] = set()
        self._member_live: set[int] = set(
            s for s in world.extra_servers if s != self.rank
        )
        # scale-in: servers mid-drain, and servers retired CLEANLY
        # (full-mirror promote, zero counted losses)
        self._draining_servers: set[int] = set()
        self._draining_self = False
        self._drain_deadline = 0.0
        self._drained_exit = False
        self._drained_servers: set[int] = set()
        self._clean_retire: set[int] = set()
        # harness hook: callable(alloc) that spawns a new server shard
        # (in-proc thread, subprocess, k8s pod — the harness's business)
        self.member_spawner = None
        # watermark-triggered scale-out with no spawner registered parks
        # here, visible at /fleet — the future autoscaler's feed
        self._scale_pending: Optional[dict] = None
        self._scaleout_t0: Optional[float] = None
        self._next_elastic_check = 0.0
        self._elastic_cooldown_until = 0.0
        # member rank -> published (host, port), for TCP joiners
        self._member_addrs: dict[int, tuple] = {}

        # when each server's death was first observed here (MTTR t0)
        self._server_eof_at: dict[int, float] = {}
        # servers whose inbound connection EOF was HANDLED by this
        # reactor: the reader enqueues PEER_EOF behind the connection's
        # last frame, so handling it proves the replication tail drained.
        # A failed SEND proves nothing of the sort (frames may still be
        # queued inbound) — promotion must key on THIS set, not on
        # _server_eof_at, or a buddy that merely failed a send to the
        # dying server would seal the mirror over unapplied SS_REPL
        # frames and drop an acked put uncountably
        self._server_tail_drained: set[int] = set()
        # (dead server, old seqno) pairs already counted in
        # failover_lost: the owner's (possibly re-sent) fetch of the
        # same lost unit must not count it again
        self._counted_lost: set[tuple[int, int]] = set()
        # SS_SERVER_DEAD arrived before the dead server's own EOF: hold
        # the promotion until the EOF drains the replication tail (or the
        # deadline passes — the death may predate any connection to us)
        self._pending_promotion: dict[int, float] = {}
        # server EOF observed during termination: ambiguous (a finished
        # peer exits, closing connections) — suspected dead, declared
        # only if the world has not completed by the deadline
        self._suspect_servers: dict[int, float] = {}
        # dead server -> wall-clock until which the TA_HOME_TAKEOVER
        # remap is periodically re-announced: the promote-time fan-out is
        # one-shot best-effort, and a connect refused under load would
        # otherwise leave a client waiting out its whole failover window
        self._takeover_renotify: dict[int, float] = {}
        self._next_renotify = 0.0
        # takeover translations: clients and servers keep addressing
        # adopted state by the DEAD server's numbering (stamped fo_from
        # by the reroute), translated here to the buddy's fresh ids
        self._adopted_units: dict[tuple[int, int], int] = {}
        self._adopted_commons: dict[tuple[int, int], int] = {}
        self._adopted_tombs: set[tuple[int, int]] = set()
        # in-flight migration batches by (routed dest -> token -> units):
        # a destination dying mid-transit would otherwise lose the units
        # serialized inside the unacked SS_MIGRATE_WORK
        self._mig_token = 0
        self._migrate_pending: dict[int, dict[int, list]] = {}
        self.died = False  # this server's own (injected) connectivity death
        # app ranks whose connection died before finalize (reclaim policy);
        # a rank that reconnects (network churn, not death) is resurrected
        self._dead_ranks: set[int] = set()
        self._resurrected: set[int] = set()
        # Duplicate-request tolerance: the transport's reconnect (and the
        # client's _send_retry above it) can deliver a request twice — the
        # frame may have been delivered before the socket error. Each
        # destructive RPC dedups its own way:
        #   puts    — per-sender window of accepted ids (idempotent ack);
        #   reserve — echoed rqseqno (a dup re-park would double-pin);
        #   get     — at-most-once cache of the last consumed response
        #             per sender (the consume is unrepeatable);
        #   common  — last fetched prefix seqno (re-serve w/o recount).
        self._seen_puts: dict[int, tuple[set, deque]] = {}
        self._seen_rqseqnos: dict[int, tuple[set, deque]] = {}
        self._last_get_resp: dict[int, tuple[int, Msg]] = {}
        self._last_common: dict[int, int] = {}
        self._seen_forfeits: dict[int, tuple[set, deque]] = {}

        self._next_seqno = 1
        self.peers: dict[int, _PeerState] = {
            s: _PeerState() for s in world.server_ranks
        }

        # stealing state
        # ranks with an outstanding RFR -> send time. The timestamp is
        # the loss-recovery hook: an SS_RFR (or its response) eaten by a
        # one-way partition or a dying link would otherwise hide the
        # requester from every later match pass forever — _periodic
        # re-arms entries older than _rfr_timeout (stray late responses
        # are already handled by the rqseqno match in _on_rfr_resp)
        self._rfr_out: dict[int, float] = {}
        self._rfr_timeout = max(5.0, 20.0 * cfg.qmstat_interval)
        self._rfr_excluded: dict[int, set[int]] = {}  # rank -> servers struck out
        # remote fused fetch: units whose payload left in a
        # payload-carrying SS_RFR_RESP but whose SS_DELIVERED/UNRESERVE
        # resolution has not arrived. They stay pinned under their lease;
        # a rank-death sweep treats them as delivered (the payload may
        # already be at the requester — re-enqueueing could run it twice)
        self._relay_inflight: dict[int, int] = {}  # seqno -> for_rank
        # ranks whose get_work_stream reported an empty bank (FA_STREAM_IDLE):
        # only then do their prefetch-flagged reserves count as parked for
        # exhaustion voting; any delivery to the rank clears the mark
        self._stream_idle: set[int] = set()
        # ranks whose prefetch entries were swept by a rank-death reclaim:
        # if the rank resurrects (the EOF was churn), its stream still
        # counts those reserves as in flight, so the next idle note is
        # answered with enough ADLB_RETRY responses to re-arm the
        # phantom slots instead of hanging the stream forever
        self._swept_streams: set[int] = set()
        # steal/broadcast event qmstat: rate limiter for the
        # empty->nonempty immediate broadcasts
        self._last_qmstat_event = 0.0
        # push state: query_id -> seqno offered; receiver side: query_id -> reserved bytes
        self._push_seq = 0
        self._push_offered: dict[int, int] = {}
        self._push_reserved: dict[int, int] = {}
        # migration batches sent but not yet acked by the destination —
        # in-flight work the exhaustion vote must see (units inside an
        # unacked SS_MIGRATE_WORK live in no wq anywhere)
        self._migrate_unacked = 0
        # src server -> highest planner migration-batch id received from
        # it (per-source: transport ordering only holds per sender pair)
        self._mig_acks: dict[int, int] = {}
        self._last_event_snap = 0.0
        # put-event task deltas accumulated while the min-gap rate limit
        # holds; flushed as ONE batched SS_STATE_DELTA (parallel per-unit
        # lists) the moment the gap elapses, so the balancer's inventory
        # view tracks a streaming producer within one gap instead of one
        # unit per gap (round 4 — the round-3 hotspot startup stall)
        self._pending_delta: list[tuple[int, int, int, int]] = []
        self._delta_deadline = float("inf")

        # termination state
        self.no_more_work = False
        self.done_by_exhaustion = False
        self.done = False
        self._finalized: set[int] = set()
        self._end1_pending = False  # END_1 token held until local apps finish
        self._end1_sent_at = 0.0    # last kick (the lost-END watchdog's t0)
        self._ending = False  # shutdown ring underway: peer EOFs are benign
        self._exhaust_held_since: Optional[float] = None
        self._exhaust_inflight = False
        self._exhaust_sent_at = 0.0
        self._exhaust_token_id = 0
        self.activity = 0  # puts accepted + reservations handed out

        # balancer state (master only, tpu mode). The snapshot table is a
        # SnapshotStore (a dict that versions its own mutations) so the
        # ledger's sync touches only changed ranks instead of walking all
        # S snapshots every round; in-place mutations below bump() it.
        from adlb_tpu.balancer.ledger import SnapshotStore

        self._snapshots: SnapshotStore = SnapshotStore()
        self._solver = None
        self._balancer: Optional[_BalancerWorker] = None
        if cfg.balancer == "tpu" and self.is_master:
            self._balancer = _BalancerWorker(self)
        # "hungry" = some requester is parked somewhere in the world whose
        # requested types new inventory could satisfy, so an untargeted put
        # of such a type is worth snapshotting immediately. Gates the
        # put-side event snapshots: without it every put pays the O(wq)
        # snapshot walk even when nobody is waiting (a measurable GIL tax
        # on compute-bound workloads). Type-aware so a permanently parked
        # collector of targeted answers (gfmc's master waiting on TYPE_D,
        # which only ever arrives as targeted puts the planner never sees)
        # does not keep the whole world snapshotting. Master tracks parked
        # types from the snapshots it already receives and broadcasts only
        # set changes. A stale-low flag merely defers discovery to the
        # balancer's periodic snapshot heartbeat.
        self._hungry = False  # some parked requester exists (any type)
        self._hungry_any = False  # a parked requester accepts any type
        self._hungry_types: frozenset = frozenset()
        from adlb_tpu.balancer.hungry import HungryTracker

        self._hungry_tracker = HungryTracker()  # master only
        self._park_res_local: dict[int, bool] = {}  # rank -> last park local?
        self._req_sigs: dict[int, tuple] = {}  # src -> last parked-req set
        self._next_idle_snap = 0.0  # slow snapshot heartbeat when not hungry

        # stats (InfoKey surface, reference src/adlb.c:3072-3141)
        self.stats = {k: 0.0 for k in InfoKey}
        self._rq_wait_sum = 0.0
        self._rq_wait_n = 0
        self._loop_t0 = time.monotonic()
        self._loops = 0

        self._abort_event = abort_event
        self._aborted = False

        # unified metrics registry (adlb_tpu/obs/metrics.py): the event
        # counters the old ad-hoc _ds_counters dict held, plus queue-depth
        # gauges/timelines sampled on the periodic tick, plus whatever the
        # transport (per-tag msgs/bytes, send/recv latency) and the
        # balancer engine (round duration, plan age, pairs) record into
        # the same store. DS_LOG, STAT_APS contributions, the ops
        # endpoint's /metrics, and flight-record artifacts all read it.
        self.metrics = Registry(self.rank)
        attach(self.ep, self.metrics)
        self._m_puts = self.metrics.counter("puts")
        self._m_reserves = self.metrics.counter("reserves")
        self._m_rfrs = self.metrics.counter("rfrs")
        self._m_pushes = self.metrics.counter("pushes")
        # failure/reclaim surface (on_worker_failure="reclaim")
        self._m_rank_dead = self.metrics.counter("rank_dead")
        self._m_leases_reclaimed = self.metrics.counter("leases_reclaimed")
        self._m_targeted_dropped = self.metrics.counter("targeted_dropped")
        self._m_reconnects = self.metrics.counter("rank_reconnects")
        # gray-failure surface (lease expiry / quarantine / backpressure)
        self._m_leases_expired = self.metrics.counter("leases_expired")
        self._m_quarantined = self.metrics.counter("quarantined")
        self._m_put_backoffs = self.metrics.counter("put_backoff")
        self._m_heartbeats = self.metrics.counter("heartbeats")
        # tail-hedging surface (Config(hedge_budget_frac) > 0,
        # runtime/hedge.py): manager + counters exist ONLY when armed —
        # an unhedged world's metric snapshots (and therefore its
        # gossip frames) stay byte-identical to an unhedged build
        if cfg.hedge_budget_frac > 0:
            self.hedges = HedgeManager(cfg.hedge_budget_frac)
            self._m_hedges_launched = self.metrics.counter("hedges_launched")
            self._m_hedges_won = self.metrics.counter("hedges_won")
            self._m_hedges_fenced = self.metrics.counter("hedges_fenced")
        else:
            self.hedges = None
        # per-scan memo of the owner-labelled lease-expiry cells (the
        # local stall-signature window for the hedge trigger), plus the
        # decaying rank -> deadline suspicion map it feeds
        self._hedge_expiry_memo: dict[str, float] = {}
        self._hedge_suspect_until: dict[int, float] = {}
        self._g_leases = self.metrics.gauge("leases_outstanding")
        self._g_lease_age = self.metrics.gauge("lease_age_max_s")
        self._g_quarantined = self.metrics.gauge("quarantined")
        self._g_mem_pressure = self.metrics.gauge("mem_pressure")
        # spill tier (Config(spill_dir)): bytes/units currently on disk,
        # spill-out and fault-in counts, and fault-in latency
        self._m_spills = self.metrics.counter("spill_outs")
        self._m_faultins = self.metrics.counter("spill_faultins")
        self._g_spill_bytes = self.metrics.gauge("spill_bytes")
        self._g_spill_units = self.metrics.gauge("spill_units")
        self._h_faultin = self.metrics.histogram("spill_faultin_s")
        # failover surface (on_server_failure="failover")
        self._m_server_dead = self.metrics.counter("server_dead")
        self._m_failover_promoted = self.metrics.counter("failover_promoted")
        self._m_failover_lost = self.metrics.counter("failover_lost")
        self._g_repl_lag = self.metrics.gauge("repl_lag")
        # durable-service surface (wal_dir / jobs): WAL depth (entries
        # not yet durable) and fsync lag ride /metrics next to repl_lag
        self._g_wal_depth = self.metrics.gauge("wal_depth")
        self._g_wal_lag = self.metrics.gauge("wal_fsync_lag_ms")
        self._m_wal_syncs = self.metrics.counter("wal_syncs")
        self._m_jobs_done = self.metrics.counter("jobs_done")
        self._g_fo_mttr = self.metrics.gauge("failover_mttr_ms")
        # elastic-membership surface: counted ONCE fleet-wide (attach/
        # detach at the home server, joins/drains at the master)
        self._m_attached = self.metrics.counter("ranks_attached")
        self._m_detached = self.metrics.counter("ranks_detached")
        self._m_servers_joined = self.metrics.counter("servers_joined")
        self._m_servers_drained = self.metrics.counter("servers_drained")
        self._g_epoch = self.metrics.gauge("member_epoch")
        self._g_scaleout_mttr = self.metrics.gauge("scaleout_mttr_ms")
        self._g_wq = self.metrics.gauge("wq_depth")
        self._g_rq = self.metrics.gauge("rq_depth")
        self._ts_wq = self.metrics.timeseries("wq_depth")
        self._ts_rq = self.metrics.timeseries("rq_depth")
        # last STAT_APS world aggregate seen at the master (served by the
        # ops endpoint's /metrics as the world-aggregated rows)
        self.last_aggregate = None
        self.ops = None

        # server-side tracing: handler + balancer-round spans into the
        # same Chrome-trace stream as client API calls (pid = role)
        self.tracer = (
            Tracer(self.rank, pid=PID_SERVER, process_name="servers")
            if cfg.trace
            else None
        )
        self._span_names: dict[Tag, str] = {}

        # unit-lifecycle tracing (Config(trace_sample), obs/journey.py):
        # sampled units carry a span list stamped at every hop; terminal
        # events close them into journeys feeding the unit_stage_s
        # histograms, the closed-journey store, and (when trace=True)
        # flow events in the Chrome-trace stream
        self.journeys = JourneyRecorder(
            self.rank, self.metrics, tracer=self.tracer
        )
        # tail-based promotion (Config(trace_tail)): "auto" arms iff the
        # world is observed (ops endpoint configured) — unobserved
        # worlds keep the untraced-put frame identity
        self.journeys.tail = cfg.trace_tail == "on" or (
            cfg.trace_tail == "auto" and cfg.ops_port is not None
        )
        # traced puts whose ack is held for the WAL group commit:
        # (src, put_id) -> unit, stamped "wal_commit" when the covering
        # fsync releases the ack
        self._trace_wal_pending: dict[tuple[int, int], WorkUnit] = {}

        # ---- fleet metrics plane (SS_OBS_SYNC gossip) ----
        # armed only for observed worlds (ops endpoint configured):
        # non-master servers ship delta-encoded registry snapshots +
        # closed journeys to the master every obs_sync_interval; the
        # master merges them for /metrics, /healthz staleness, and
        # /trace/units. Unobserved worlds pay zero gossip traffic.
        self._obs_sync_armed = (
            cfg.ops_port is not None and cfg.obs_sync_interval > 0
        )
        self._obs_last: dict = {}   # delta-snapshot memo (what we sent)
        self._obs_seq = 0
        # master side: rank -> cumulative registry view; rank -> (seq,
        # received-at monotonic) staleness ledger; fleet journey store
        self._fleet_snaps: dict[int, dict] = {}
        self._fleet_seen: dict[int, tuple[int, float]] = {}
        self._journeys_fleet: deque = deque(maxlen=4096)
        # tail-promoted journeys (why != head): the /trace/tails store
        self._tails_fleet: deque = deque(maxlen=2048)
        # per-(job, type) p99 thresholds the master computes from the
        # merged fleet unit_total_s cells (cached per obs tick; replies
        # to gossip frames carry it back to the closing servers)
        self._tail_thr_cache: list = []
        # continuous profiler (Config(profile_hz)): _prof is the OWNED
        # instance (this server started it, gossips it, stops it);
        # _prof_shared is whatever profiler lives in this process (for
        # phase markers — in-proc worlds share one across servers)
        self._prof = None
        self._prof_shared = None
        self._prof_memo: dict = {}
        self._phase_names: dict[Tag, str] = {}
        # master side: per-rank gossiped cumulative folded stacks and
        # sealed sampling windows (the /profile merge + tail join)
        self._prof_fleet: dict[int, dict] = {}
        self._prof_windows: dict[int, deque] = {}
        self._last_aggregate_at = 0.0
        # jobs whose gauges the last gauge tick set (so a dropped
        # partition's gauges get zeroed exactly once, not left frozen)
        self._job_gauged: set[int] = set()
        # ---- SLO engine (obs/slo.py) ----
        # master-only evaluator over the merged fleet registry; created
        # at init from Config(slo=...) or lazily by the first POST /slo.
        # _slo_alerts_wire: compact rows riding SS_OBS_SYNC replies
        # (publish-by-swap — the gossip path reads it mid-reply);
        # _slo_alerts_remote: what a NON-master last heard from the
        # master (the fleet-wide agreement surface); _incidents: the
        # live bundles /incidents serves, newest last.
        self._slo_engine = None
        self._slo_alerts_wire: list = []
        self._slo_alerts_remote: list = []
        self._next_slo_eval = 0.0  # cadence gate (slo_eval_interval)
        self._incidents: deque = deque(maxlen=32)
        self._m_alerts_firing = self.metrics.gauge("alerts_firing")
        if self._obs_sync_armed and self.is_master and cfg.slo:
            from adlb_tpu.obs.slo import SloEngine

            eng = SloEngine(cfg.slo_eval_interval
                            or cfg.obs_sync_interval)
            for doc in cfg.slo:
                eng.add(doc)
            self._slo_engine = eng

        # ---- fleet controller (control/controller.py) ----
        # master-only closed loop over the existing actuators (scale
        # plane + job quotas), riding the obs tick like the SLO engine.
        # Unconfigured worlds carry only this None — no thread, no
        # counters, no per-tick work.
        self._controller = None
        self._next_control = 0.0  # cadence gate (control_interval)
        if self._obs_sync_armed and self.is_master and cfg.control:
            from adlb_tpu.control import Controller

            self._controller = Controller(
                {
                    "dry_run": cfg.control_dry_run,
                    "min_servers": cfg.control_min_servers,
                    "max_servers": cfg.control_max_servers,
                    "cooldown_s": cfg.control_cooldown_s,
                    "scaleout_pressure": cfg.control_scaleout_pressure,
                    "scalein_pressure": cfg.control_scalein_pressure,
                },
                eval_interval=(cfg.control_interval
                               or cfg.obs_sync_interval),
            )

        # timers
        now = time.monotonic()
        self._next_state_sync = now
        self._next_gauge_sample = now  # first tick samples immediately
        self._next_obs_sync = (
            now + cfg.obs_sync_interval
            if self._obs_sync_armed
            else float("inf")
        )
        self._next_lease_scan = (
            now + cfg.lease_timeout_s if self._lease_armed else float("inf")
        )
        self._next_hedge_scan = (
            now + cfg.hedge_min_age_ms / 1e3
            if self.hedges is not None else float("inf")
        )
        self._next_exhaust_check = now + cfg.exhaust_check_interval
        self._next_ds_log = now
        # since-last-DS_LOG bookkeeping for the reference's 11-counter
        # heartbeat payload (reference src/adlb.c:3222-3259)
        self._ds_last = {"events": 0, "ss": 0, "reserves": 0, "immed": 0,
                         "parked": 0, "rfr_failed": 0}
        self._n_reserve_immed = 0
        self._n_rfr_failed = 0

        # periodic cluster-wide stats ring (reference src/adlb.c:712-753)
        self.resolved_reserves = 0
        self._pstats_seq = 0
        self._next_pstats = (
            now + cfg.periodic_log_interval
            if cfg.periodic_log_interval > 0
            else float("inf")
        )

        # debug plumbing (reference src/adlb.c:176-179,558-710); the obs
        # recorder adds JSON post-mortem artifacts on top of the text ring
        self.flight = FlightRecorder(
            self.rank, out_dir=cfg.flight_dir, role="server"
        )
        self.flight.metrics = self.metrics
        self.flight.context = {
            "is_master": self.is_master,
            "balancer": cfg.balancer,
            "nservers": world.nservers,
            "num_app_ranks": world.num_app_ranks,
            "local_apps": sorted(self.local_apps),
        }
        self.tag_freq: dict[Tag, int] = {}
        self._next_selfdiag = (
            now + cfg.selfdiag_interval
            if cfg.selfdiag_interval > 0
            else float("inf")
        )

        if cfg.restore_path:
            self._restore_from_checkpoint(cfg.restore_path)
        if self.wal is not None:
            # cold restart: shard-load + log replay through the replica
            # mirror machinery, adopted into the live queues. Runs after
            # the metrics/flight plumbing exists (it records) and never
            # alongside restore_path (Config refuses the combination).
            self._recover_from_wal()

        self._handlers = {
            Tag.PEER_EOF: self._on_peer_eof,
            Tag.FA_CHECKPOINT: self._on_fa_checkpoint,
            Tag.SS_CHECKPOINT: self._on_ss_checkpoint,
            Tag.FA_PUT: self._on_put,
            Tag.FA_PUT_COMMON: self._on_put_common,
            Tag.FA_BATCH_DONE: self._on_batch_done,
            Tag.FA_DID_PUT_AT_REMOTE: self._on_did_put_at_remote,
            Tag.FA_RESERVE: self._on_reserve,
            Tag.FA_STREAM_IDLE: self._on_stream_idle,
            Tag.FA_STREAM_CANCEL: self._on_stream_cancel,
            Tag.FA_GET_RESERVED: self._on_get_reserved,
            Tag.FA_GET_COMMON: self._on_get_common,
            Tag.FA_HEARTBEAT: self._on_heartbeat,
            Tag.FA_GET_QUARANTINED: self._on_get_quarantined,
            Tag.FA_JOB_CTL: self._on_fa_job_ctl,
            Tag.SS_JOB_CTL: self._on_ss_job_ctl,
            Tag.FA_NO_MORE_WORK: self._on_fa_no_more_work,
            Tag.FA_LOCAL_APP_DONE: self._on_local_app_done,
            Tag.FA_ABORT: self._on_fa_abort,
            Tag.FA_INFO_NUM_WORK_UNITS: self._on_info_num,
            Tag.FA_INFO_GET: self._on_info_get,
            Tag.SS_QMSTAT: self._on_qmstat,
            Tag.SS_RFR: self._on_rfr,
            Tag.SS_RFR_RESP: self._on_rfr_resp,
            Tag.SS_UNRESERVE: self._on_unreserve,
            Tag.SS_DELIVERED: self._on_delivered,
            Tag.SS_PUSH_QUERY: self._on_push_query,
            Tag.SS_PUSH_QUERY_RESP: self._on_push_query_resp,
            Tag.SS_PUSH_WORK: self._on_push_work,
            Tag.SS_PUSH_DEL: self._on_push_del,
            Tag.SS_MOVING_TARGETED_WORK: self._on_moving_targeted,
            Tag.SS_NO_MORE_WORK: self._on_ss_no_more_work,
            Tag.SS_EXHAUST_CHK_1: self._on_exhaust_chk,
            Tag.SS_EXHAUST_CHK_2: self._on_exhaust_chk,
            Tag.SS_DONE_BY_EXHAUSTION: self._on_done_by_exhaustion,
            Tag.SS_END_1: self._on_end_1,
            Tag.SS_END_2: self._on_end_2,
            Tag.SS_ABORT: self._on_ss_abort,
            Tag.SS_PERIODIC_STATS: self._on_periodic_stats,
            Tag.SS_STATE: self._on_state,
            Tag.SS_STATE_DELTA: self._on_state_delta,
            Tag.SS_HUNGRY: self._on_hungry,
            Tag.SS_PLAN_MATCH: self._on_plan_match,
            Tag.SS_PLAN_MIGRATE: self._on_plan_migrate,
            Tag.SS_MIGRATE_WORK: self._on_migrate_work,
            Tag.SS_MIGRATE_ACK: self._on_migrate_ack,
            Tag.FA_MEMBER: self._on_fa_member,
            Tag.SS_MEMBER: self._on_ss_member,
            Tag.SS_RANK_DEAD: self._on_rank_dead,
            Tag.SS_COMMON_FORFEIT: self._on_common_forfeit,
            Tag.SS_REPL: self._on_repl,
            Tag.SS_SERVER_DEAD: self._on_server_dead,
            Tag.SS_MASTER_TAKEOVER: self._on_master_takeover,
            Tag.SS_OBS_SYNC: self._on_obs_sync,
        }

    @staticmethod
    def _make_wq(cfg: Config):
        """Pick the work-queue implementation: C++ core (ctypes) when wanted
        and buildable, else the pure-Python indexed queue. The spill tier
        forces the Python queue: spilling swaps a unit's payload residency
        in place, which the C++ core's unit storage cannot express."""
        if cfg.native_queues == "off" or cfg.spill_dir is not None:
            return WorkQueue()
        try:
            from adlb_tpu.native.wq import NativeWorkQueue

            return NativeWorkQueue()
        except (RuntimeError, OSError, ImportError):
            if cfg.native_queues == "on":
                raise
            return WorkQueue()

    # ------------------------------------------------------------------ loop

    def run(self) -> None:
        aprintf(
            self.cfg.aprintf_flag, self.rank,
            f"server starting (master={self.is_master}, "
            f"apps={sorted(self.local_apps)}, balancer={self.cfg.balancer})",
        )
        try:
            if self.cfg.ops_port is not None and self.is_master:
                from adlb_tpu.obs.ops_server import maybe_start

                self.ops = maybe_start(self, self.cfg)
                if self.ops is not None:
                    aprintf(
                        self.cfg.aprintf_flag, self.rank,
                        f"ops endpoint on 127.0.0.1:{self.ops.port}",
                    )
                    self._announce_ops_endpoint()
            # standing deputy bootstrap: the master's FIRST replication
            # flush already carries the brain, so a death at any point
            # after startup finds a promotable deputy (the config-borne
            # SLO/control state rides it; live POSTs stream deltas)
            if self.is_master and self._failover and self.repl is not None:
                self._repl_brain()
                if self._slo_engine is not None:
                    for o in self._slo_engine.objectives:
                        self.repl.log_slo(dict(o))
                if self._controller is not None:
                    self.repl.log_control(self._controller.policy_doc())
            if self.cfg.profile_hz > 0:
                # per-PROCESS singleton: in-proc worlds run many server
                # threads in one interpreter and the sampler sees them
                # all — the first starter owns (and gossips) it, the
                # rest share it for phase markers only
                self._prof = profile.start(self.cfg.profile_hz, self.rank)
            self._prof_shared = profile.active()
            if self._balancer is not None:
                self._balancer.start()
            if self.rank not in self.world.spec.server_ranks:
                # scale-out shard: the reactor is up — announce ready so
                # the master publishes us live (rings, buddy walks) and
                # directs the donor bootstrap at us
                self.ep.send(
                    self.world.master_server_rank,
                    msg(Tag.SS_MEMBER, self.rank, mop="ready"),
                )
            self._run_loop()
        finally:
            profile.stop(self._prof)
            self._prof = None
            if self.ops is not None:
                self.ops.stop()
            if self.wal is not None:
                # final group commit: any held acks flush (the clients
                # are gone at clean shutdown, so this is about the tail
                # entries being durable for the next incarnation)
                try:
                    for app, resp in self.wal.tick(
                        time.monotonic(), force=True
                    ):
                        self._send_app(app, resp)
                except OSError:
                    pass
                self.wal.close()
            if self.spill is not None:
                self.spill.close()
            if self._balancer is not None:
                self._balancer.stop()
                # bounded join: a straggler round finishing after teardown
                # would otherwise overlap (and contend with) the next world
                # in back-to-back in-process runs; never wait on a wedged
                # device solve, though — the thread is a daemon
                self._balancer.join(timeout=1.0)
            self._notify_debug_server_end()
            aprintf(
                self.cfg.aprintf_flag, self.rank,
                f"server exiting (wq_max={self.wq.max_count}, "
                f"activity={self.activity}, aborted={self._aborted})",
            )

    def _run_loop(self) -> None:
        try:
            self._run_loop_inner()
        except OSError as e:
            # this server's own connectivity died (fault-injected
            # disconnect): under the failover policy that is the simulated
            # server death — exit quietly as the casualty (the buddy is
            # taking over), never as a world error
            plan = getattr(self.ep, "plan", None)
            if (
                self.cfg.on_server_failure == "failover"
                and plan is not None
                and getattr(plan, "disconnected", False)
            ):
                self.flight.record(
                    f"own connectivity lost ({e!r}); exiting as failover "
                    f"casualty"
                )
                self.died = True
                self.done = True
                return
            raise

    def _run_loop_inner(self) -> None:
        interval = (
            self.cfg.balancer_interval
            if self.cfg.balancer == "tpu"
            else self.cfg.qmstat_interval
        )
        profile.register_thread("reactor")
        prof = self._prof_shared  # None when profiling is off: the
        # phase markers below cost one None check per transition then
        while not self.done:
            if self._abort_event is not None and self._abort_event.is_set():
                # every server dumps state on abort (the reference gives a
                # 10 s grace for exactly this, src/adlb.c:2508-2526)
                if not self._aborted:
                    self._aborted = True
                    self.flight.record("abort event observed")
                    self.flight.dump(reason="abort")
                return
            now = time.monotonic()
            self._loops += 1
            self._periodic(now, interval)
            deadline = min(
                self._next_state_sync,
                self._delta_deadline,
                self._next_exhaust_check if self.is_master else now + 1.0,
                self._next_ds_log
                if self.world.use_debug_server
                else now + 1.0,
                self._next_pstats if self.is_master else now + 1.0,
                # the WAL's group-commit deadline: held put acks must
                # release on time even when no traffic arrives
                self.wal.next_deadline(now + 1.0)
                if self.wal is not None
                else now + 1.0,
            )
            if prof is not None:
                # "decode" covers the recv wait + frame decode; a sample
                # landing in the idle wait shows poll/recv frames, which
                # the stack itself disambiguates from decode work
                prof.set_phase("decode")
            m = self.ep.recv(timeout=max(deadline - time.monotonic(), 0.0))
            t0 = time.monotonic()
            if m is not None:
                # one submission batch per reactor tick: every doorbell
                # write / channel send this burst of handlers produces
                # drains at the flush below, so N responses cost O(1)
                # wakeups instead of O(N) (PR 8's named follow-up)
                self.ep.submit_begin()
                try:
                    self._handle(m)
                    # drain whatever else is queued before paying the
                    # poll timeout — but bounded, so periodic duties
                    # (state sync, watchdog heartbeat, exhaustion
                    # checks) still run under sustained load
                    for _ in range(128):
                        if self.done or time.monotonic() >= deadline:
                            break
                        if prof is not None:
                            prof.set_phase("decode")
                        m2 = self.ep.recv(timeout=0.0)
                        if m2 is None:
                            break
                        self._handle(m2)
                finally:
                    if prof is not None:
                        prof.set_phase("submit_flush")
                    self.ep.submit_flush()
            self._flush_repl()
            self._flush_wal()
            self.stats[InfoKey.LOOP_TOP_TIME] += time.monotonic() - t0

    def _handle(self, m: Msg) -> None:
        """Dispatch one message; when tracing, the handler runs inside a
        ``srv:<TAG>`` span on the server tracer so the merged Chrome
        trace shows the server side of every client round trip."""
        handler = self._handlers.get(m.tag)
        if handler is None:
            raise AdlbError(f"server {self.rank}: no handler for {m.tag}")
        self.tag_freq[m.tag] = self.tag_freq.get(m.tag, 0) + 1
        prof = self._prof_shared
        if prof is not None:
            # phase marker: a profiler sample interrupting this handler
            # attributes to handler:<TAG> (cached string, edge-set)
            pname = self._phase_names.get(m.tag)
            if pname is None:
                pname = self._phase_names[m.tag] = f"handler:{m.tag.name}"
            prof.set_phase(pname)
        if self._lease_armed and self.world.is_app(m.src):
            # every frame from an app rank is liveness evidence: protocol
            # traffic piggybacks the heartbeat, FA_HEARTBEAT only covers
            # the idle-but-computing gaps
            self._last_heard[m.src] = time.monotonic()
        if self._dead_ranks and m.src in self._dead_ranks and (
            m.tag.name.startswith("FA_")
        ):
            # a rank we declared dead is talking again: the EOF was
            # connection churn, not process death. Resurrect it — but its
            # reserve/put gets a retriable code so the request re-arrives
            # after this server's reclaim fan-out has settled (its old
            # leases/rq entries are gone either way; see USERGUIDE §7).
            self._resurrect(m.src)
            if m.tag in (Tag.FA_RESERVE, Tag.FA_PUT):
                resp_tag = (
                    Tag.TA_RESERVE_RESP
                    if m.tag is Tag.FA_RESERVE
                    else Tag.TA_PUT_RESP
                )
                # _send_app, not a raw send: these could be trailing
                # buffered frames from a rank that really IS dead, whose
                # connection refuses — that must not crash the reactor
                self._send_app(
                    m.src,
                    msg(resp_tag, self.rank, rc=ADLB_RETRY,
                        put_id=m.data.get("put_id"),
                        rqseqno=m.data.get("rqseqno")),
                )
                return
        tr = self.tracer
        if tr is None:
            handler(m)
            return
        name = self._span_names.get(m.tag)
        if name is None:
            name = self._span_names[m.tag] = f"srv:{m.tag.name}"
        with tr.span(name, src=m.src):
            handler(m)

    def _periodic(self, now: float, interval: float) -> None:
        if self._ctl_inbox:
            # ops-thread control requests (POST /jobs): serviced on the
            # reactor thread, verdicts handed back via their events
            self._drain_ctl_inbox()
        if self.wal is not None:
            self._g_wal_depth.set(self.wal.depth)
            self._g_wal_lag.set(self.wal.fsync_lag_ms(now))
            if self.wal.maybe_compact(self):
                self._release_wal_acks(self.wal.take_compact_acks())
        if self._draining_self:
            # scale-in drain parked on in-flight push custody: the
            # deadline bounds a pusher that died mid-handshake
            self._maybe_finish_drain()
        if (
            self.is_master and self._end1_pending and not self.done
            and not self._aborted and not self._member_pending
            and not self._takeover_pending
            and self._finalized >= self.local_apps
            and now - self._end1_sent_at
            > 10 * self.cfg.exhaust_check_interval
        ):
            # lost-END recovery: an epoch-voided END_1 dies at the
            # voiding server; once the gossip converges the epochs,
            # re-kick under the current one (token-less ring — the
            # generous deadline, not an id, bounds duplicates)
            self._forward_end1(
                {"origin": self.rank, "epoch": self.world.epoch}
            )
        if (
            self._takeover_pending
            and now >= self._takeover_pending["deadline"]
        ):
            # succession barrier timeout: a wedged survivor must not
            # park termination forever — it is on its way to an EOF-
            # declared death, which releases the barrier anyway
            self.flight.record(
                "master takeover barrier timeout unacked="
                f"{sorted(self._takeover_pending['need'])}"
            )
            self._master_takeover_done()
        if self._rfr_out:
            # RFR loss recovery: a request (or its response) lost to a
            # one-way partition / dying link has no acker — re-arm the
            # requester and re-match immediately instead of hiding it
            # from the balancer until the end of time
            stale = [
                r for r, t0 in self._rfr_out.items()
                if now - t0 > self._rfr_timeout
            ]
            for r in stale:
                del self._rfr_out[r]
                self.flight.record(f"rfr timeout for rank {r}: re-armed")
            for entry in self.rq.entries() if stale else ():
                if entry.world_rank in stale:
                    self._try_rfr(entry)
        if self._member_pending:
            # membership fan-out/ack barrier timeout: a wedged server
            # must not park a joiner forever. The change already applied
            # at every RESPONSIVE server (the fan-out is idempotent), so
            # answer the joiner; the silent server is on its way to an
            # EOF-declared death anyway.
            for tok, p in list(self._member_pending.items()):
                if now >= p["deadline"]:
                    del self._member_pending[tok]
                    self.flight.record(
                        f"member barrier timeout tok={tok} "
                        f"unacked={sorted(p['need'])}"
                    )
                    self._member_reply(p)
        if (
            self.is_master
            and self.cfg.elastic_scaleout == "auto"
            and self.cfg.max_malloc_per_server > 0
            and now >= self._next_elastic_check
        ):
            self._next_elastic_check = now + 0.25
            self._maybe_autoscale(now)
        if self._pending_promotion:
            # SS_SERVER_DEAD arrived but the dead server's own EOF has
            # not: promote at the deadline anyway (the death may predate
            # any connection from it to us)
            for dead, deadline in list(self._pending_promotion.items()):
                if now >= deadline:
                    del self._pending_promotion[dead]
                    self._promote(dead)
        if self._suspect_servers:
            # server EOF during termination: a finished peer's normal
            # exit if the world completes promptly, a real death if not
            for srv, deadline in list(self._suspect_servers.items()):
                if now >= deadline:
                    del self._suspect_servers[srv]
                    if not self.done and srv not in self._dead_servers:
                        self._declare_server_dead(srv)
        if self._takeover_renotify and now >= self._next_renotify:
            # repair lost TA_HOME_TAKEOVER notes (the promote-time fan-out
            # is one connect attempt per rank): re-announce ~1/s to every
            # live, unfinalized app until the client windows close
            self._next_renotify = now + 1.0
            for dead, until in list(self._takeover_renotify.items()):
                if now >= until:
                    del self._takeover_renotify[dead]
                    continue
                for r in self.world.app_ranks:
                    if r in self._dead_ranks or r in self._finalized:
                        continue
                    try:
                        self.ep.send(
                            r, msg(Tag.TA_HOME_TAKEOVER, self.rank,
                                   dead=dead, epoch=self.world.epoch),
                            connect_grace=0.25,
                        )
                    except OSError:
                        pass
        if self._pending_delta and now >= self._delta_deadline:
            self._flush_task_deltas(now)
        if self._lease_armed and now >= self._next_lease_scan:
            # scan well inside the timeout so detection latency is
            # bounded by ~1.25x lease_timeout_s, not 2x
            self._next_lease_scan = now + max(
                self.cfg.lease_timeout_s / 4.0, 0.01
            )
            self._scan_leases(now)
        if self.hedges is not None and now >= self._next_hedge_scan:
            # hedge-trigger scan (runtime/hedge.py): well inside the
            # age floor, same cadence logic as the lease scan above
            self._next_hedge_scan = now + max(
                self.cfg.hedge_min_age_ms / 4e3, 0.01
            )
            self._scan_hedges(now)
        if now >= self._next_gauge_sample:
            # queue-depth gauges + bounded timelines, sampled on their
            # OWN cadence (Config(gauge_interval), 0.25 s default),
            # decoupled from the balancer tick: in tpu mode the state
            # sync runs at balancer_interval (20 ms), and paying the
            # gauge walk + its ctypes GIL crossings 50x/s on the reactor
            # thread was a measured slice of the r01->r05 tpu pop-latency
            # drift (see docs/pop_latency_r06.md). Observability loses
            # nothing: the timelines still cover the same history,
            # just at post-mortem resolution.
            self._next_gauge_sample = now + max(
                interval, self.cfg.gauge_interval)
            wq_d, wq_avail, wq_bytes = self.wq.depth_sample()
            rq_d = len(self.rq)
            self._g_wq.set(wq_d)
            self._g_rq.set(rq_d)
            self._ts_wq.append(now, wq_d)
            self._ts_rq.append(now, rq_d)
            m = self.metrics
            m.gauge("wq_untargeted_avail").set(wq_avail)
            m.gauge("wq_bytes").set(wq_bytes)
            m.gauge("rq_oldest_age_s").set(
                self.rq.oldest_age(now, stream_idle=self._stream_idle)
            )
            self._g_mem_pressure.set(self.mem.pressure)
            if self.spill is not None:
                self._g_spill_bytes.set(self.mem.spilled)
                self._g_spill_units.set(len(self.spill))
            self._g_leases.set(len(self.leases))
            self._g_lease_age.set(self.leases.oldest_age(now))
            self._g_quarantined.set(len(self.quarantine))
            # per-job depth/bytes/age gauges (non-default namespaces
            # only — job 0 IS the world-level gauges above): what
            # /jobs/<id> serves live and the autoscaler watches
            gauged = set()
            for jid in self.wq.job_ids():
                if jid == 0:
                    continue
                part = self.wq.part(jid)
                if part is None:
                    continue
                gauged.add(jid)
                jl = str(jid)
                m.gauge("job_wq_depth", job=jl).set(part.count)
                m.gauge("job_wq_bytes", job=jl).set(part.total_bytes)
                m.gauge("job_oldest_age_s", job=jl).set(max(
                    (now - u.time_stamp for u in part.units()),
                    default=0.0,
                ))
            # a dropped partition (job kill) leaves its gauges frozen at
            # the last sample — zero them once so a dead job cannot
            # report phantom backlog to /jobs/<id> forever (the change
            # also rides the next gossip delta, healing the master)
            for jid in self._job_gauged - gauged:
                jl = str(jid)
                m.gauge("job_wq_depth", job=jl).set(0)
                m.gauge("job_wq_bytes", job=jl).set(0)
                m.gauge("job_oldest_age_s", job=jl).set(0.0)
            self._job_gauged = gauged
            # quota-backoff totals ride the same gossip so /jobs/<id>
            # (and the controller) sees the FLEET's admission pressure,
            # not just the master's shard; cumulative, so no zeroing
            for job in self.jobs.values():
                if job.job_id and job.backoffs:
                    m.gauge(
                        "job_backoffs", job=str(job.job_id)
                    ).set(job.backoffs)
        if self._obs_sync_armed and now >= self._next_obs_sync:
            self._next_obs_sync = now + self.cfg.obs_sync_interval
            if self.is_master:
                # the master's own journeys join the fleet stores
                # directly (head -> /trace/units, promoted -> tails)
                self._route_journeys(self.journeys.take_done())
                if self.journeys.tail:
                    # refresh the per-(job, type) p99 promotion
                    # thresholds from the merged fleet unit_total_s
                    # cells; install locally and cache for the gossip
                    # replies that carry them to the closing servers
                    thr = self._tail_thresholds()
                    self._tail_thr_cache = [
                        [j, t, v] for (j, t), v in thr.items()
                    ]
                    self.journeys.tail_thr = thr
                if self._slo_engine is not None:
                    self._slo_evaluate(now)
                if self._controller is not None:
                    self._control_evaluate(now)
            else:
                self._obs_sync_send()
        if now >= self._next_state_sync:
            self._next_state_sync = now + interval
            if self.cfg.balancer == "tpu":
                # The snapshot walk is O(wq); at the fast balancer cadence
                # it is a real GIL tax on compute-bound workloads. Walk it
                # fast only while it matters: someone is parked (_hungry)
                # AND this server could contribute — untargeted inventory
                # for the solve, or its own parked requesters whose fresh
                # stamps keep them re-plannable. Memory pressure also
                # qualifies (planner-side admission wants fresh nbytes).
                # Otherwise a slow heartbeat (parks themselves send event
                # snapshots immediately).
                # rq length first: it is a plain Python len, while
                # untargeted_avail crosses into the C core (a GIL
                # release/re-acquire per call on this hot tick)
                relevant = self._hungry and (
                    len(self.rq) > 0 or self.wq.untargeted_avail > 0
                )
                if (
                    relevant
                    or self.mem.under_pressure
                    or now >= self._next_idle_snap
                ):
                    self._next_idle_snap = now + 0.25
                    self._send_snapshot()
                if (
                    self.wq.has_job_units(
                        min_job=max(self.cfg.balancer_max_jobs, 1)
                    )
                    and now - self._last_qmstat_event
                    >= self.cfg.qmstat_event_gap
                ):
                    # DOCUMENTED FALLBACK: namespaces the planner does
                    # not cover — ALL non-default jobs when
                    # balancer_max_jobs is 1 (the pre-PR 19 world), else
                    # only OVERFLOW jobs (id >= balancer_max_jobs) —
                    # reach across servers via the RFR pull, driven by
                    # the same per-job qmstat gossip steal mode uses.
                    # Rate-limited by the steal-mode event limiter: this
                    # used to fire every balancer-cadence tick, an S-1
                    # fan-out each time.
                    self._last_qmstat_event = now
                    self._broadcast_qmstat()
            else:
                self._broadcast_qmstat()
            if self.mem.under_pressure:
                # spill tier first (local disk beats shipping bytes to a
                # peer); pushes remain for what spilling cannot absorb
                if self.spill is not None:
                    self._maybe_spill()
                if self.mem.under_pressure:
                    self._try_push()
        if self.is_master and self.cfg.balancer == "tpu":
            self._flush_hungry_shrink(now)
        if self.is_master and now >= self._next_exhaust_check:
            self._next_exhaust_check = now + self.cfg.exhaust_check_interval
            self._check_exhaustion(now)
            self._check_job_exhaustion(now)
        if self.world.use_debug_server and now >= self._next_ds_log:
            self._next_ds_log = now + self.cfg.debug_log_interval
            self._send_ds_log()
        if self.is_master and now >= self._next_pstats:
            self._next_pstats = now + self.cfg.periodic_log_interval
            self._kick_periodic_stats(now)
        if now >= self._next_selfdiag:
            self._next_selfdiag = now + self.cfg.selfdiag_interval
            self_diagnosis(self, now, stuck_after=self.cfg.selfdiag_stuck_after)

    # ------------------------------------------------------- helpers

    def _pin(self, seqno: int, rank: int) -> None:
        """Pin + lease: every reservation handed out is owned, so a dead
        owner's pins are findable in O(its leases) at reclaim time."""
        if self.spill is not None:
            # delivery needs the bytes: fault a spilled payload in at
            # reservation time (covers fused, handle, RFR, plan paths)
            unit = self.wq.get(seqno)
            if unit is not None and unit.spilled:
                self._unspill(unit)
        self.wq.pin(seqno, rank)
        self.leases.grant(seqno, rank)
        if self.journeys.live:
            unit = self.wq.get(seqno)
            if unit is not None and unit.spans is not None:
                # every reservation path (local match, plan enactment,
                # RFR service) pins here — the "match" hop
                self.journeys.stamp(unit, "match")
        if self.wlog is not None:
            self.wlog.log_pin(seqno, rank)

    def _consume(self, unit) -> None:
        """Remove a fetched/inlined unit and settle its lease + memory."""
        if self.hedges is not None:
            # every delivery funds the per-job hedge bucket, and a
            # delivery IS the terminal that closes a hedge race (the
            # universal settle: fused, handle, and relay-confirm paths
            # all pass through here)
            self.hedges.credit(unit.job)
            self._hedge_settle(unit)
        self.wq.remove(unit.seqno)
        self.leases.release(unit.seqno)
        self.mem.free(len(unit.payload))
        if self.wlog is not None:
            self.wlog.log_consume(unit.seqno)

    def _send_app(self, app: int, m: Msg) -> bool:
        """Protocol response to an app rank. Under the reclaim policy a
        dead destination (already marked, or its connection refuses) is
        absorbed — returns False so the caller can requeue anything it
        consumed — instead of crashing the reactor; the EOF-driven
        reclaim owns the rest of the cleanup."""
        if self.cfg.on_worker_failure == "reclaim" and app in self._dead_ranks:
            return False
        try:
            self.ep.send(app, m)
            return True
        except OSError:
            if self.cfg.on_worker_failure != "reclaim":
                raise
            self.flight.record(
                f"send to rank {app} failed mid-death ({m.tag.name})"
            )
            return False

    def _requeue_consumed(self, unit, prefix_fetched: bool = True) -> None:
        """Put a consumed-but-undeliverable unit back on the queue (its
        requester died between match and delivery). ``prefix_fetched``:
        whether the dead requester already accounted a prefix get for
        this member (True on the Get_reserved path, which orders
        common-first; False on the fused path, whose response carries
        only the suffix)."""
        if unit.target_rank >= 0 and unit.target_rank in self._dead_ranks:
            # targeted at the dead requester itself: dropping IS the
            # reclaim outcome (no other rank may take targeted work), and
            # the rank-dead sweep already ran, so nobody else will drop
            # it. A fused (suffix-only) drop must still forfeit the
            # member's prefix share — no get will ever account it; the
            # Get_reserved path's share was accounted by the dead
            # requester's common-first fetch.
            if not prefix_fetched:
                self._forfeit_common(unit.common_seqno,
                                     unit.common_server_rank)
            self._m_targeted_dropped.inc()
            if unit.spans is not None:
                self.journeys.close(unit, "dropped")
            self.flight.record(
                f"targeted_dropped rank={unit.target_rank} "
                f"seqno={unit.seqno} (undelivered)"
            )
            return
        unit.pinned = False
        unit.pin_rank = -1
        if self._bump_attempts(unit, in_wq=False):
            # retry budget exhausted: quarantined, not re-queued. A fused
            # member's prefix share was never accounted (suffix-only
            # delivery) and never will be — forfeit it so the prefix
            # still GCs under its live members.
            if unit.common_seqno >= 0 and not prefix_fetched:
                self._forfeit_common(unit.common_seqno,
                                     unit.common_server_rank)
            return
        self.mem.alloc(len(unit.payload))
        self.wq.add(unit)
        if self.wlog is not None:
            self.wlog.log_put(unit, -1, None)
        if unit.common_seqno >= 0 and prefix_fetched:
            # the dead requester fetched the prefix before this fetch
            # (Get_reserved orders common-first); the re-consumption
            # fetches it again
            self._forfeit_common(unit.common_seqno, unit.common_server_rank,
                                 op="credit")
        self.flight.record(f"lease_reclaimed seqno={unit.seqno} (undelivered)")
        self._m_leases_reclaimed.inc()

    # ------------------------------------------------------- spill tier
    # Config(spill_dir): above the spill watermark, cold/large parked
    # payloads move to the per-server spill file (runtime/spill.py) and
    # only metadata stays resident; every path that reads payload bytes
    # (pin->deliver, push, migrate, checkpoint, quarantine) faults them
    # back in first. The accountant tracks resident vs spilled bytes, so
    # watermarks/pushes/admission act on real RAM occupancy.

    def _spill_unit(self, unit) -> None:
        n = len(unit.payload)
        self.spill.put(unit.seqno, unit.payload)
        # remove/re-add so the queue's byte accounting and indexes track
        # the residency change (the heaps tolerate the duplicate entry)
        self.wq.remove(unit.seqno)
        unit.payload = b""
        unit.spilled = True
        unit.spill_len = n
        self.wq.add(unit)
        self.mem.note_spill(n)
        self._m_spills.inc()

    def _unspill(self, unit) -> None:
        """Fault a spilled payload back in (transparent to callers)."""
        if self.spill is None or not unit.spilled:
            return
        t0 = time.monotonic()
        payload = self.spill.take(unit.seqno)
        in_wq = self.wq.get(unit.seqno) is unit
        if in_wq:
            self.wq.remove(unit.seqno)
        unit.payload = payload
        unit.spilled = False
        unit.spill_len = 0
        if in_wq:
            self.wq.add(unit)
        self.mem.note_faultin(len(payload))
        self._m_faultins.inc()
        self._h_faultin.observe(time.monotonic() - t0)

    def _spill_drop(self, unit) -> None:
        """A spilled unit is being dropped outright (dead target, killed
        job): release its spill-file entry and accounting."""
        if self.spill is not None and unit.spilled:
            self.mem.note_spill_drop(self.spill.discard(unit.seqno))
            unit.spilled = False
            unit.spill_len = 0

    def _maybe_spill(self, incoming: int = 0) -> None:
        """Move cold parked payloads to disk until ``incoming`` more
        bytes fit under the spill watermark. Victims are unpinned
        resident payloads, largest first (fewest records for the most
        relief), oldest first among equals (cold before hot). O(wq)
        scan — runs only above the watermark, where the alternative is
        backpressure."""
        if self.spill is None or self.mem.max_bytes <= 0:
            return
        frac = self.cfg.spill_watermark_frac or self.mem.soft_frac
        need = self.mem.curr + incoming - frac * self.mem.max_bytes
        if need <= 0:
            return
        # top-K by (size desc, age) instead of a full sort: the scan is
        # already O(wq) per call under sustained pressure, and K=64
        # victims per pass cover any realistic per-put deficit (a
        # size-ordered resident index is the follow-up if profiles ever
        # show this pass on top)
        import heapq as _heapq

        cands = _heapq.nsmallest(
            64,
            (
                (-len(u.payload), u.time_stamp, u.seqno, u)
                for u in self.wq.units()
                if not u.pinned and not u.spilled and len(u.payload) > 0
            ),
        )
        freed = 0
        for _nlen, _ts, _sq, u in cands:
            if freed >= need:
                break
            freed += len(u.payload)
            self._spill_unit(u)

    def _spill_fault_in_all(self) -> None:
        """Restore every spilled payload (checkpoint shards and WAL
        compaction snapshots serialize payload bytes; a transient
        resident spike beats silently checkpointing empty payloads)."""
        if self.spill is None:
            return
        for u in list(self.wq.units()):
            if u.spilled:
                self._unspill(u)

    def _least_loaded_peer(self, nbytes_needed: int = 0) -> int:
        """Least-loaded peer believed to have room for nbytes_needed, else
        least-loaded overall, else -1."""
        cap = self.cfg.max_malloc_per_server
        best, best_bytes = -1, None
        fallback, fallback_bytes = -1, None
        for s, st in self.peers.items():
            if s == self.rank:
                continue
            if fallback_bytes is None or st.nbytes < fallback_bytes:
                fallback, fallback_bytes = s, st.nbytes
            if cap > 0 and st.nbytes + nbytes_needed > cap:
                continue
            if best_bytes is None or st.nbytes < best_bytes:
                best, best_bytes = s, st.nbytes
        return best if best >= 0 else fallback

    def _reserve_resp(
        self, app_rank: int, rc: int, unit: Optional[WorkUnit] = None,
        holder: Optional[int] = None, fetch: bool = False,
        rqseqno: Optional[int] = None,
    ) -> None:
        # ``rqseqno`` echoes the request id being answered: reservation
        # responses are otherwise indistinguishable, and the prefetch
        # pipeline needs to match (and dedup re-sent duplicates of)
        # responses against its outstanding slots by id
        if rc != ADLB_SUCCESS:
            self._send_app(
                app_rank,
                msg(Tag.TA_RESERVE_RESP, self.rank, rc=rc, rqseqno=rqseqno),
            )
            return
        self.resolved_reserves += 1
        if fetch and (holder is None or holder == self.rank):
            # fused reserve+get (no reference analogue — upstream always
            # pays a second round trip, src/adlb.c:2976-3025): the unit is
            # local, so consume it now and inline the payload in the
            # reservation response. A batch-common unit inlines only its
            # SUFFIX plus the prefix handle: the client assembles from
            # its prefix cache (one fetch per client per prefix, hits
            # accounted via SS_COMMON_FORFEIT so server refcounts stay
            # exact).
            self._consume(unit)
            fields = dict(
                rc=ADLB_SUCCESS,
                rqseqno=rqseqno,
                work_type=unit.work_type,
                prio=unit.prio,
                work_len=unit.work_len,
                answer_rank=unit.answer_rank,
                payload=unit.payload,
                time_on_q=time.monotonic() - unit.time_stamp,
            )
            if unit.target_rank >= 0:
                # a stream closing early re-puts banked units; carrying
                # the targeting lets it preserve the only-R-may-run-it
                # contract instead of re-pooling the unit untargeted
                fields["target_rank"] = unit.target_rank
            if unit.common_len > 0:
                # The member's prefix share is accounted by the CLIENT
                # (fetch on miss, forfeit note on cache hit) — it cannot
                # be accounted here at consume time, because the prefix
                # must outlive the GC until every member's client has
                # actually read the bytes. A client that dies between
                # this delivery and its accounting therefore leaks the
                # prefix for the rest of the run — the same bounded-leak
                # trade-off the reclaim credit path documents
                # (CommonStore.credit), never a lost unit.
                fields.update(
                    common_len=unit.common_len,
                    common_server=unit.common_server_rank,
                    common_seqno=unit.common_seqno,
                )
            delivered = self._send_app(
                app_rank, msg(Tag.TA_RESERVE_RESP, self.rank, **fields)
            )
            if not delivered:
                # the dead requester never fetched the prefix (fused
                # responses carry only the suffix), so no common credit
                self._requeue_consumed(unit, prefix_fetched=False)
            elif unit.spans is not None:
                # fused local delivery is terminal: the payload left
                # with the reservation response
                self.journeys.deliver_close(unit)
            return
        handle = WorkHandle(
            seqno=unit.seqno,
            server_rank=holder if holder is not None else self.rank,
            common_len=unit.common_len,
            common_server_rank=unit.common_server_rank,
            common_seqno=unit.common_seqno,
        )
        self._send_reserve_handle(app_rank, unit, handle, rqseqno)

    def _reserve_resp_batch(
        self, app_rank: int, units: list, rqseqno: Optional[int] = None,
    ) -> None:
        """One TA_RESERVE_RESP carrying several consumed local units
        (get_work_batch); the binary codec carries the parallel per-unit
        fields as blist/list/flist kinds (codec.py ids 80-84)."""
        now = time.monotonic()
        self.resolved_reserves += len(units)
        for u in units:
            self._consume(u)
        delivered = self._send_app(
            app_rank,
            msg(
                Tag.TA_RESERVE_RESP,
                self.rank,
                rc=ADLB_SUCCESS,
                rqseqno=rqseqno,
                payloads=[u.payload for u in units],
                work_types=[u.work_type for u in units],
                prios=[u.prio for u in units],
                answer_ranks=[u.answer_rank for u in units],
                times_on_q=[now - u.time_stamp for u in units],
            ),
        )
        if not delivered:
            for u in units:
                self._requeue_consumed(u)
        else:
            for u in units:
                if u.spans is not None:
                    self.journeys.deliver_close(u)

    def _send_reserve_handle(self, app_rank, unit, handle,
                             rqseqno=None) -> None:
        # an undeliverable handle needs no requeue here: the unit stays
        # pinned under the dead rank's lease, which the EOF-driven
        # reclaim releases
        self._send_app(
            app_rank,
            msg(
                Tag.TA_RESERVE_RESP,
                self.rank,
                rc=ADLB_SUCCESS,
                rqseqno=rqseqno,
                work_type=unit.work_type,
                prio=unit.prio,
                handle=handle.to_ints(),
                work_len=unit.work_len,
                answer_rank=unit.answer_rank,
            ),
        )

    def _kick_periodic_stats(self, now: float) -> None:
        """Master starts a stats token around the server ring; each server
        adds its contribution and forwards; back at the master the sum is
        printed as STAT_APS chunks (reference ``src/adlb.c:712-753,
        2391-2465``)."""
        from adlb_tpu.runtime import stats as pstats

        if self.no_more_work or self.done_by_exhaustion:
            return  # ring peers may already be shutting down
        self._pstats_seq += 1
        token = {
            "seq": self._pstats_seq,
            "t0": now,
            "entries": {self.rank: pstats.contribution(self)},
        }
        if self.world.nservers == 1:
            self.last_aggregate = pstats.aggregate(token, time.monotonic())
            self._last_aggregate_at = time.monotonic()
            pstats.emit_stat_aps(self.last_aggregate)
            return
        self._forward_pstats(token)

    def _forward_pstats(self, token: dict) -> None:
        # best-effort: a ring peer that already exited must not kill the
        # sender — stats tokens are droppable, the protocol ring is not
        try:
            self.ep.send(
                self._ring_next_live(),
                msg(Tag.SS_PERIODIC_STATS, self.rank, token=token),
            )
        except OSError:
            pass

    def _on_periodic_stats(self, m: Msg) -> None:
        from adlb_tpu.runtime import stats as pstats

        token = m.token
        if self.is_master:
            # kept for the ops endpoint: /metrics serves this aggregate
            # (stamped with its ring seq + an age, so a stalled ring
            # reads as STALE data, not live data)
            self.last_aggregate = pstats.aggregate(token, time.monotonic())
            self._last_aggregate_at = time.monotonic()
            pstats.emit_stat_aps(self.last_aggregate)
            return
        token["entries"][self.rank] = pstats.contribution(self)
        self._forward_pstats(token)

    # ------------------------------------------- fleet metrics plane

    def _obs_sync_send(self) -> None:
        """Ship this server's delta registry snapshot + closed journeys
        to the master (the SS_OBS_SYNC gossip tick). Best-effort like
        the stats ring: the master dying aborts the world anyway."""
        journeys = self.journeys.take_done()
        delta = self.metrics.delta_snapshot(self._obs_last)
        # an empty delta still goes: the seq-stamped frame doubles as
        # the staleness heartbeat /healthz reads — an idle server stays
        # distinguishable from a wedged one
        self._obs_seq += 1
        extra = {}
        if self._prof is not None:
            # owned profiler: changed-stacks-only cumulative counters +
            # windows sealed since the last ship (lost frames heal —
            # same contract as the registry delta)
            pd = self._prof.take_delta(self._prof_memo)
            if pd:
                extra["prof"] = pd
        try:
            self.ep.send(
                self.world.master_server_rank,
                msg(Tag.SS_OBS_SYNC, self.rank, snap=delta,
                    journeys=journeys, seq=self._obs_seq, **extra),
            )
        except OSError:
            pass  # droppable; cumulative values heal on the next tick

    def _on_obs_sync(self, m: Msg) -> None:
        if not self.is_master:
            # master -> server reply: the tail-promotion thresholds
            # computed from the FLEET hist cells (list-of-triples wire
            # form; swapped whole so a mid-close read stays consistent)
            thr = m.data.get("thr")
            if thr is not None:
                self.journeys.tail_thr = {
                    (int(j), int(t)): float(v) for j, t, v in thr
                }
            # the master's alert rows ride the same reply (append-only
            # wire contract: an older server simply never reads the
            # key) — swapped whole, the fleet-wide agreement surface
            alerts = m.data.get("alerts")
            if alerts is not None:
                self._slo_alerts_remote = alerts
            return
        base = self._fleet_snaps.get(m.src) or {
            "counters": {}, "gauges": {}, "histograms": {},
        }
        snap = m.data.get("snap") or {}
        # publish-by-swap, never update-in-place: the ops HTTP thread
        # iterates these dicts concurrently, and an in-place update
        # inserting a first-seen key would blow up its iteration —
        # a fresh dict swapped in under the GIL is always safe to read
        self._fleet_snaps[m.src] = {
            "rank": m.src,
            "counters": {**base["counters"],
                         **snap.get("counters", {})},
            "gauges": {**base["gauges"], **snap.get("gauges", {})},
            "histograms": {**base["histograms"],
                           **snap.get("histograms", {})},
        }
        self._fleet_seen[m.src] = (
            int(m.data.get("seq", 0)), time.monotonic()
        )
        self._route_journeys(m.data.get("journeys") or ())
        pd = m.data.get("prof")
        if pd:
            # cumulative folded stacks overwrite per key (publish-by-
            # swap for the ops thread, like the registry snapshots);
            # sealed windows append to the per-rank ring
            base = self._prof_fleet.get(m.src) or {}
            stacks = pd.get("stacks")
            if stacks:
                self._prof_fleet[m.src] = {**base, **stacks}
            wins = self._prof_windows.get(m.src)
            if wins is None:
                wins = self._prof_windows[m.src] = deque(
                    maxlen=profile.MAX_WINDOWS
                )
            for w in pd.get("win") or ():
                wins.append(w)
        reply = {}
        if self.journeys.tail and self._tail_thr_cache:
            reply["thr"] = self._tail_thr_cache
        if self._slo_alerts_wire:
            reply["alerts"] = self._slo_alerts_wire
        if reply:
            # carry the promotion thresholds + alert rows back on the
            # same plane (best-effort, 1 small frame per gossip tick
            # per server)
            try:
                self.ep.send(
                    m.src, msg(Tag.SS_OBS_SYNC, self.rank, **reply)
                )
            except OSError:
                pass

    def _route_journeys(self, journeys) -> None:
        """Sort closed journeys into the master's fleet stores by their
        retention reasons: head-sampled -> /trace/units (the PR 12
        store), any tail-promotion reason -> /trace/tails. A journey
        can be both (a head-sampled unit that also blew the p99)."""
        for j in journeys:
            why = j.get("why") or ["head"]
            if "head" in why:
                self._journeys_fleet.append(j)
            if any(w != "head" for w in why):
                self._tails_fleet.append(j)

    def _tail_thresholds(self) -> dict:
        """Per-(job, type) p99 of unit total latency over the MERGED
        fleet ``unit_total_s`` cells (the master's live registry + every
        gossiped snapshot). Hysteresis: a cell arms only past
        TAIL_MIN_COUNT closes, so a cold histogram promotes nothing."""
        agg: dict[tuple, list] = {}

        def add(bounds, counts, n, job, typ):
            key = (job, typ)
            cur = agg.get(key)
            if cur is None:
                agg[key] = [list(bounds), list(counts), n]
            elif len(cur[1]) == len(counts):
                cur[1] = [a + b for a, b in zip(cur[1], counts)]
                cur[2] += n

        for (name, labels), h in self.metrics._stable_items()[2]:
            if name != "unit_total_s":
                continue
            lab = dict(labels)
            try:
                add(h.bounds, h.counts, h.n,
                    int(lab["job"]), int(lab["type"]))
            except (KeyError, ValueError):
                continue
        for snap in list(self._fleet_snaps.values()):
            for key, h in snap.get("histograms", {}).items():
                if not key.startswith("unit_total_s{"):
                    continue
                lab = dict(
                    kv.split("=", 1)
                    for kv in key[len("unit_total_s{"):-1].split(",")
                )
                try:
                    add(h["bounds"], h["counts"], h["count"],
                        int(lab["job"]), int(lab["type"]))
                except (KeyError, ValueError):
                    continue
        return {
            key: quantile_of(bounds, counts, n, 0.99)
            for key, (bounds, counts, n) in agg.items()
            if n >= TAIL_MIN_COUNT
        }

    def _slo_evaluate(self, now: float) -> None:
        """One SLO evaluation tick (master reactor, inside the obs-sync
        tick): merge own registry + every gossiped snapshot, compute
        which live members are stale per the /healthz rule, run the
        engine, then act on transitions — flight event each, the
        ``alerts_firing`` gauge, the wire rows the gossip replies carry
        fleet-wide, and a live incident bundle on a page FIRING."""
        if now < self._next_slo_eval:
            return
        if self.cfg.slo_eval_interval > 0:
            self._next_slo_eval = now + self.cfg.slo_eval_interval
        eng = self._slo_engine
        eng.note_epoch(self.world.epoch, now)
        merged = Registry.merge(
            [self.metrics.snapshot()] + list(self._fleet_snaps.values())
        )
        # staleness per the /healthz rule: a gossiping member whose last
        # snapshot is older than 3 sync intervals has gone quiet — its
        # last values still sit in _fleet_snaps (merged above), so it
        # degrades the evaluation rather than silently zeroing it
        cadence = self.cfg.obs_sync_interval
        stale = [
            r for r, (_seq, at) in list(self._fleet_seen.items())
            if now - at > 3.0 * cadence
        ]
        transitions = eng.evaluate(now, merged, stale)
        self._slo_alerts_wire = eng.wire
        self._m_alerts_firing.set(eng.firing)
        for tr in transitions:
            self.flight.record(
                f"slo_alert {tr['name']} {tr['from']}->{tr['to']} "
                f"sev={tr['severity']} burn_fast={tr['burn_fast']} "
                f"burn_slow={tr['burn_slow']}"
            )
            if tr["to"] == "FIRING" and tr["severity"] == "page":
                self._slo_capture_incident(tr, now)

    def _slo_capture_incident(self, transition: dict, now: float) -> None:
        """Page-severity FIRING: snapshot the evidence bundle (tails +
        stacks + metrics delta + topology) while the world is still
        degraded, write it atomically to flight_dir, and keep it in the
        ring /incidents serves. Evidence capture must never take the
        reactor down — a failed bundle is a flight note, not a crash."""
        from adlb_tpu.obs import flight as _flight
        from adlb_tpu.obs.slo import build_incident

        try:
            doc = build_incident(self, self._slo_engine, transition, now)
        except Exception as e:  # noqa: BLE001 — evidence is best-effort
            self.flight.record(f"incident_build_failed {e!r:.120}")
            return
        path = _flight.write_incident(
            self.flight.out_dir, transition["name"], doc
        )
        if path is not None:
            doc["artifact"] = path
        self._incidents.append(doc)
        self.flight.record(
            f"incident_captured {transition['name']} "
            f"suspects={doc['suspect_ranks']} artifact={path}"
        )

    def _control_evaluate(self, now: float) -> None:
        """One controller tick (master reactor, inside the obs-sync
        tick, right after the SLO evaluation whose ``firing`` count it
        consumes): assemble the sensor frame, run the decision rules,
        enact what came back ``act`` (rewriting the outcome to
        ``enacted``/``error`` in place — the controller's history holds
        the same dicts, so GET /control shows what actually happened),
        flight-record every new decision, and swap the published status
        doc the ops thread serves."""
        if now < self._next_control:
            return
        ctl = self._controller
        self._next_control = now + ctl.eval_interval
        inputs = self._control_inputs(now)
        for d in ctl.evaluate(now, inputs):
            if d["outcome"] == "act":
                self._control_enact(d)
            a = d["action"]
            self.flight.record(
                f"control {d['rule']} kind={a['kind']} "
                f"outcome={d['outcome']}"
            )
        ctl.publish(now, inputs)

    def _control_enact(self, d: dict) -> None:
        """Drive the actuator an ``act`` decision names. An actuator
        error never takes the reactor down — it lands in the decision
        record (outcome ``error``) and the rule retries after its
        cooldown window."""
        a = d["action"]
        kind = a["kind"]
        try:
            if kind == "scale_out":
                # spawnerless worlds park the request (satellite: the
                # registration drain services it) — still an action
                res = self._request_scale_out(
                    f"controller:{d['rule']}",
                    hot_rank=a.get("hot_rank"),
                )
                d["result"] = res
                if res.get("error"):
                    raise RuntimeError(res["error"])
            elif kind == "scale_in":
                d["result"] = self._handle_ctl({"op": "scale_in"})
            elif kind in ("throttle", "unthrottle"):
                # quota -1 restores unlimited (jobs.apply's update
                # encoding); the fanout reaches every server's admission
                # gate, not just the master's shard
                self._job_ctl_fanout(
                    "update", int(a["job"]), quota=int(a["quota_bytes"])
                )
            else:
                raise ValueError(f"unknown action kind {kind!r}")
        except Exception as e:  # noqa: BLE001 — record, don't crash
            d["outcome"] = "error"
            d["error"] = repr(e)
            return
        d["outcome"] = "enacted"
        self._controller.actions_total += 1
        self.metrics.counter("control_actions", kind=kind).inc()

    def _control_inputs(self, now: float) -> dict:
        """The controller's sensor frame, assembled from state the
        master reactor already holds: live membership, per-rank memory
        pressure (own meter + peer-advertised nbytes over cap), per-job
        fleet totals (own partitions + the gossiped ``job_*`` gauges),
        the SLO engine's firing count, quota backoffs, oldest lease."""
        cap = float(self.cfg.max_malloc_per_server)
        live = [
            s for s in self.world.server_ranks
            if s not in self._dead_servers
            and s not in self._draining_servers
            and self._is_live_member(s)
        ]
        pressure: dict = {}
        if cap > 0:
            pressure[self.rank] = self.mem.curr / cap
            for s in live:
                if s == self.rank:
                    continue
                p = self.peers.get(s)
                if p is not None:
                    pressure[s] = p.nbytes / cap
        jobs: dict = {}
        snaps = list(self._fleet_snaps.values())
        for job in self.jobs.values():
            jid = job.job_id
            if jid == 0:
                continue
            part = self.wq.part(jid)
            depth = part.count if part is not None else 0
            nbytes = part.total_bytes if part is not None else 0
            age = max(
                (now - u.time_stamp for u in part.units()), default=0.0
            ) if part is not None else 0.0
            backoffs = job.backoffs
            jl = f"job={jid}"
            for snap in snaps:
                g = snap.get("gauges") or {}
                depth += int(g.get(f"job_wq_depth{{{jl}}}", 0) or 0)
                nbytes += int(g.get(f"job_wq_bytes{{{jl}}}", 0) or 0)
                age = max(age, float(
                    g.get(f"job_oldest_age_s{{{jl}}}", 0.0) or 0.0))
                backoffs += int(g.get(f"job_backoffs{{{jl}}}", 0) or 0)
            jobs[jid] = {
                "depth": depth, "bytes": nbytes,
                "oldest_age_s": round(age, 3), "backoffs": backoffs,
                "quota_bytes": job.quota_bytes, "state": job.state,
            }
        return {
            "live_servers": len(live),
            "pressure": pressure,
            "firing": (self._slo_engine.firing
                       if self._slo_engine is not None else 0),
            "jobs": jobs,
            "backoffs": sum(j["backoffs"] for j in jobs.values()),
            "oldest_lease_s": self.leases.oldest_age(now),
            "epoch": self.world.epoch,
        }

    def _satisfy_parked(self, entry: RqEntry, unit: WorkUnit,
                        holder: Optional[int] = None,
                        local: bool = True) -> None:
        """Hand a unit to a parked requester and account the wait.

        ``local`` records how this rank's park got resolved — by a local
        put (True) or by cross-server delivery (push/migrate/unreserve
        re-match, False) — which drives the adaptive park-event gating in
        ``_on_reserve``."""
        self.rq.remove_entry(entry)
        # a delivery un-idles a streaming rank (it has work to chew on)
        # and demotes its sibling pipeline slots behind other ranks'
        # entries, so scarce inventory spreads instead of piling onto
        # one consumer's bank
        self._stream_idle.discard(entry.world_rank)
        self.rq.demote_rank(entry.world_rank)
        self._park_res_local[entry.world_rank] = local
        self._rfr_excluded.pop(entry.world_rank, None)
        wait = time.monotonic() - entry.time_stamp
        self._rq_wait_sum += wait
        self._rq_wait_n += 1
        self.activity += 1
        self._job_activity(entry.job)
        self._reserve_resp(entry.world_rank, ADLB_SUCCESS, unit,
                           holder=holder, fetch=entry.fetch,
                           rqseqno=entry.rqseqno)

    def _match_rq(self) -> None:
        """Re-scan parked requesters against the local queue — run after any
        event that adds/unpins work (the local analogue of the reference's
        ``check_remote_work_for_queued_apps``, ``src/adlb.c:3536-3579``)."""
        progressed = True
        while progressed:
            progressed = False
            for entry in self.rq.entries():
                unit = self.wq.find_match(entry.world_rank, entry.req_types,
                                          job=entry.job)
                if unit is not None:
                    self._pin(unit.seqno, entry.world_rank)
                    # _match_rq runs after cross-server deliveries
                    # (push/migrate arrivals, unreserve compensation)
                    self._satisfy_parked(entry, unit, local=False)
                    progressed = True
                    break

    # ------------------------------------------------- checkpoint / resume
    # No reference analogue (SURVEY §5: pool serialization absent there).
    # A client's FA_CHECKPOINT reaches the master, which circulates a ring
    # token; every server writes <prefix>.<rank>.ckpt (unpinned units + the
    # batch-common store); the master acks the origin client with the total
    # unit count. Restore happens at server init from the same shards.

    def _restore_from_checkpoint(self, prefix: str) -> None:
        from adlb_tpu.runtime import checkpoint

        stray = set(checkpoint.existing_shard_ranks(prefix)) - set(
            self.world.server_ranks
        )
        if stray:
            # silently dropping higher-rank shards would lose their units;
            # the restore world must match the checkpoint's server set
            raise AdlbError(
                f"checkpoint {prefix} has shards for server ranks "
                f"{sorted(stray)} outside this world "
                f"({list(self.world.server_ranks)}); restore with the same "
                f"world shape"
            )
        units, centries = checkpoint.load_shard(
            prefix, self.rank, self.world,
            allow_legacy=self.cfg.allow_legacy_shards,
        )
        for u in units:
            payload = u.pop("payload")
            self.mem.alloc(len(payload))
            unit = WorkUnit(seqno=self._next_seqno, payload=payload,
                            home_server=self.rank, **u)
            self.wq.add(unit)
            if self.wlog is not None:
                self.wlog.log_put(unit, -1, None)
            self._next_seqno += 1
        for seqno, refcnt, ngets, buf in centries:
            self.mem.alloc(len(buf))
            self.cq.restore(seqno, refcnt, ngets, buf)
            if self.wlog is not None:
                self.wlog.log_common_put(seqno, buf)
                self.wlog.log_common_state(seqno, refcnt, ngets, 0)
        aprintf(
            self.cfg.aprintf_flag, self.rank,
            f"restored {len(units)} units, {len(centries)} common entries "
            f"from {prefix}",
        )

    def _write_checkpoint_shard(self, prefix: str) -> int:
        from adlb_tpu.runtime import checkpoint

        self._spill_fault_in_all()  # shards serialize payload bytes
        return checkpoint.save_shard(prefix, self.rank, self.wq.units(),
                                     self.cq, world=self.world)

    def _on_fa_checkpoint(self, m: Msg) -> None:
        # native clients carry the path as bytes over the TLV codec
        path = m.path.decode() if isinstance(m.path, bytes) else m.path
        fwd = msg(Tag.SS_CHECKPOINT, self.rank, path=path, client=m.src,
                  started=False)
        if self.is_master:
            self._on_ss_checkpoint(fwd)
        else:
            self.ep.send(self.world.master_server_rank, fwd)

    def _on_ss_checkpoint(self, m: Msg) -> None:
        # units inside an unacked SS_MIGRATE_WORK live in no wq; holding
        # the token until the ack lands keeps them out of the lost-update
        # window (they are then in the destination's wq, and the
        # destination is later in the ring or re-sends bounces likewise)
        if self._migrate_unacked != 0:
            # a queue, not a slot: concurrent checkpoints from different
            # clients must all complete (each blocks on its own resp)
            if not hasattr(self, "_held_checkpoints"):
                self._held_checkpoints = []
            self._held_checkpoints.append(m)
            return
        self._process_checkpoint(m)

    def _process_checkpoint(self, m: Msg) -> None:
        if self.is_master and not m.started:
            n = self._write_checkpoint_shard(m.path)
            token = {"path": m.path, "client": m.client,
                     "counts": {self.rank: n}}
            if self.world.nservers == 1:
                self._ack_checkpoint(token)
            else:
                self._ring_forward(
                    lambda nxt: msg(Tag.SS_CHECKPOINT, self.rank,
                                    started=True, token=token)
                )
            return
        token = m.token
        if self.is_master:  # token came back around
            self._ack_checkpoint(token)
            return
        token["counts"][self.rank] = self._write_checkpoint_shard(
            token["path"]
        )
        self._ring_forward(
            lambda nxt: msg(Tag.SS_CHECKPOINT, self.rank, started=True,
                            token=token)
        )

    def _ack_checkpoint(self, token: dict) -> None:
        self.ep.send(
            token["client"],
            msg(Tag.TA_CHECKPOINT_RESP, self.rank, rc=ADLB_SUCCESS,
                count=sum(token["counts"].values())),
        )

    # ------------------------------------------------------- app handlers

    @staticmethod
    def _window_seen(store: dict, src: int, req_id) -> bool:
        """Per-sender bounded replay window: True when req_id was already
        recorded (a duplicate re-sent across connection churn — possibly
        REORDERED behind newer ids by the per-connection reader threads,
        so a high-water mark or last-id check would misclassify), else
        records it."""
        entry = store.get(src)
        if entry is None:
            entry = store[src] = (set(), deque())
        ids, order = entry
        if req_id in ids:
            return True
        ids.add(req_id)
        order.append(req_id)
        if len(order) > 512:
            ids.discard(order.popleft())
        return False

    def _put_seen(self, src: int, put_id) -> bool:
        entry = self._seen_puts.get(src)
        return entry is not None and put_id in entry[0]

    def _put_record(self, src: int, put_id) -> None:
        if put_id is None:
            return
        entry = self._seen_puts.get(src)
        if entry is None:
            entry = self._seen_puts[src] = (set(), deque())
        ids, order = entry
        ids.add(put_id)
        order.append(put_id)
        if len(order) > 512:
            ids.discard(order.popleft())

    def _on_put(self, m: Msg) -> None:
        self._m_puts.inc()
        # every put tags its request with a per-client id, echoed in the
        # response (pipelined puts match out-of-band responses by it; all
        # puts get re-send dedup from it)
        put_id = m.data.get("put_id")
        if put_id is not None and self._put_seen(m.src, put_id):
            # duplicate of an already-accepted put (the client re-sent
            # after a send error): idempotent ack, nothing stored twice
            self._send_app(
                m.src,
                msg(Tag.TA_PUT_RESP, self.rank, rc=ADLB_SUCCESS,
                    put_id=put_id),
            )
            return
        if self.no_more_work or self.done_by_exhaustion:
            self.ep.send(
                m.src, msg(Tag.TA_PUT_RESP, self.rank, rc=ADLB_NO_MORE_WORK,
                           put_id=put_id)
            )
            return
        jid = int(m.data.get("job_id", 0) or 0)
        job = None
        if jid:
            job = self.jobs.ensure(jid)
            if not job.accepts_puts:
                # draining/done/killed namespace: the job's no-more-work
                self.ep.send(
                    m.src,
                    msg(Tag.TA_PUT_RESP, self.rank, rc=ADLB_NO_MORE_WORK,
                        put_id=put_id),
                )
                return
            if job.quota_bytes > 0 and m.target_rank < 0:
                # per-tenant admission quota: the job's queued bytes on
                # THIS server against its per-server cap — the PR 5
                # backpressure rc scoped to the tenant. Targeted puts
                # exempt (answer/completion traffic; stalling it
                # starves the consumers that drain the quota).
                part = self.wq.part(jid)
                used = part.total_bytes if part is not None else 0
                if used + len(m.payload) > job.quota_bytes:
                    job.backoffs += 1
                    self._m_put_backoffs.inc()
                    self.flight.record(
                        f"job_quota_backoff job={jid} src={m.src} "
                        f"used={used} quota={job.quota_bytes}"
                    )
                    self.ep.send(
                        m.src,
                        msg(Tag.TA_PUT_RESP, self.rank, rc=ADLB_BACKOFF,
                            retry_after_ms=25, put_id=put_id),
                    )
                    return
        if m.target_rank >= 0 and not self.world.is_app(m.target_rank) \
                and m.target_rank not in self._dead_ranks \
                and m.target_rank not in self.world.detached:
            # elastic membership: the CLIENT passed an above-base-world
            # target through (it cannot tell an attached member from a
            # typo) — the servers hold the authoritative membership, so
            # an unknown member is answered loudly, never parked forever
            self._send_app(
                m.src,
                msg(Tag.TA_PUT_RESP, self.rank, rc=ADLB_ERROR,
                    put_id=put_id),
            )
            return
        if m.target_rank >= 0 and (
            m.target_rank in self._dead_ranks
            or m.target_rank in self.world.detached
        ):
            # targeted at a dead (or cleanly detached) rank:
            # accept-and-drop (at-most-once — the
            # unit could never be fetched), keeping the batch-common
            # refcount correct so the prefix still GCs
            self._m_targeted_dropped.inc()
            self.flight.record(
                f"targeted_dropped rank={m.target_rank} src={m.src} "
                f"(put to dead target)"
            )
            self._forfeit_common(m.common_seqno, m.common_server)
            self._put_record(m.src, put_id)
            self._send_app(
                m.src,
                msg(Tag.TA_PUT_RESP, self.rank, rc=ADLB_SUCCESS,
                    put_id=put_id),
            )
            return
        # empty->nonempty observation must happen BEFORE the unit lands:
        # it drives the steal-mode event qmstat below (peers whose view
        # dates from the last drain believe this type has nothing)
        type_was_empty = (
            (self.cfg.balancer == "steal" or jid != 0)
            and self.cfg.qmstat_mode == "broadcast"
            and self.cfg.qmstat_event_gap > 0
            and m.target_rank < 0
            and self.wq.hi_prio_of_type(m.work_type, job=jid)
            <= ADLB_LOWEST_PRIO
        )
        payload: bytes = m.payload
        if self.spill is not None:
            # spill tier: make room from cold parked payloads BEFORE the
            # watermark checks, so a put storm over the soft watermark
            # degrades to slower-fetch (spilled cold units) instead of
            # ADLB_BACKOFF / ADLB_PUT_REJECTED
            self._maybe_spill(len(payload))
        if (
            m.target_rank < 0
            and self.mem.above_hard(len(payload))
            and not self._peer_has_room(len(payload))
        ):
            # overload backpressure (Config(mem_hard_frac) > 0): above the
            # hard watermark with nowhere to point the putter, a reject
            # hint would only bounce it between equally-full servers
            # until its retry budget aborts the producer — answer
            # ADLB_BACKOFF with a retry-after hint instead, so the
            # producer stalls (shedding load into its own pacing) while
            # consumers drain this server below the watermark.
            # UNTARGETED puts only: a targeted put is answer/completion
            # traffic bound to THIS home server (no peer can take it),
            # and stalling completions starves the very consumers whose
            # fetches drain the pressure — the classic backpressure
            # deadlock. Targeted puts fall through to the reference
            # admission path (hard reject at the cap).
            self._m_put_backoffs.inc()
            self.flight.record(
                f"put_backoff src={m.src} nbytes={len(payload)} "
                f"curr={self.mem.curr}"
            )
            self.ep.send(
                m.src,
                msg(
                    Tag.TA_PUT_RESP,
                    self.rank,
                    rc=ADLB_BACKOFF,
                    retry_after_ms=25,
                    put_id=put_id,
                ),
            )
            return
        if not self.mem.try_alloc(len(payload)):
            self.stats[InfoKey.NREJECTED_PUTS] += 1
            self.flight.record(
                f"put rejected from rank {m.src} ({len(payload)}B, "
                f"curr={self.mem.curr})"
            )
            self.ep.send(
                m.src,
                msg(
                    Tag.TA_PUT_RESP,
                    self.rank,
                    rc=ADLB_PUT_REJECTED,
                    hint=self._least_loaded_peer(len(payload)),
                    put_id=put_id,
                ),
            )
            return
        unit = WorkUnit(
            seqno=self._next_seqno,
            work_type=m.work_type,
            prio=m.prio,
            target_rank=m.target_rank,
            answer_rank=m.answer_rank,
            payload=payload,
            home_server=self.rank,
            common_len=m.common_len,
            common_server_rank=m.common_server,
            common_seqno=m.common_seqno,
            job=jid,
        )
        self._next_seqno += 1
        trace_id = m.data.get("trace_id")
        if trace_id:
            # head-sampled unit: arm the journey (put_recv stamp) before
            # anything else happens to it — the wlog append below then
            # carries the context to the buddy/WAL with the unit
            self.journeys.begin(unit, trace_id, time.monotonic())
        elif self.journeys.tail:
            # tail mode: EVERY put accumulates spans under a server-
            # minted (negative) id; whether the journey is KEPT is
            # decided at terminal close (p99 / anomalous-end promotion)
            self.journeys.begin_tail(unit, time.monotonic())
        self.wq.add(unit)
        if unit.trace_id > 0:
            # the enqueue hop separates admission work from queue wait —
            # meaningful at head-sample volume, but its delta is this
            # handler's own microseconds, so the every-unit tail arm
            # skips it (tail attribution charges the wait to "match")
            self.journeys.stamp(unit, "enqueue")
        if self.wlog is not None:
            self.wlog.log_put(unit, m.src, put_id)
        self.stats[InfoKey.MAX_WQ_COUNT] = max(
            self.stats[InfoKey.MAX_WQ_COUNT], self.wq.count
        )
        self.activity += 1
        if job is not None:
            job.puts += 1
            job.activity += 1
        self._exhaust_held_since = None
        # immediate match against parked requesters (reference
        # rq_find_rank_queued_for_type on FA_PUT_HDR, src/adlb.c:988-1042)
        entry = self.rq.find_for_type(unit.work_type, unit.target_rank,
                                      job=jid)
        if entry is not None:
            self._pin(unit.seqno, entry.world_rank)
            self._satisfy_parked(entry, unit)
        elif unit.target_rank >= 0:
            # elastic membership: a targeted put can land OFF the
            # target's home (a static client's base-modulo route cannot
            # know an attached rank's assigned home, and a rank attached
            # after the putter's view was seeded re-homes under a later
            # epoch). Announce the inventory to the target's home so its
            # TargetedDirectory redirects the rank's reserve here —
            # exactly the off-home directory the failover re-announce
            # path already maintains. Static worlds never take this
            # branch (clients route targeted puts home by construction).
            try:
                t_home = self.world.home_server(unit.target_rank)
            except KeyError:
                t_home = self.rank  # not yet a member here: the rank's
                # own reserve traffic will find it once membership lands
            if t_home != self.rank:
                self._send_srv(
                    t_home,
                    msg(Tag.SS_MOVING_TARGETED_WORK, self.rank,
                        app_rank=unit.target_rank,
                        work_type=unit.work_type,
                        from_server=-1, to_server=self.rank, count=1),
                )
        self._put_record(m.src, put_id)
        # write-ahead replication: the unit's log entry must be on the
        # wire BEFORE the accept ack, or a server death in between loses
        # an acked put uncountably (the client, once acked, never
        # re-sends). One extra one-way frame per accepted put, failover
        # mode only.
        self._flush_repl()
        resp = msg(Tag.TA_PUT_RESP, self.rank, rc=ADLB_SUCCESS,
                   put_id=put_id)
        if self.wal is not None:
            # write-ahead DURABILITY: the ack is held until the group
            # commit that fsyncs this put's entry (released immediately
            # when wal_fsync_ms == 0)
            if unit.spans is not None and put_id is not None:
                # stamp "wal_commit" when the covering fsync releases
                # this ack (see _release_wal_acks)
                self._trace_wal_pending[(m.src, put_id)] = unit
            self.wal.defer_ack(m.src, resp)
            self._flush_wal()
        else:
            self._send_app(m.src, resp)
        if (
            entry is None
            and self.cfg.balancer == "tpu"
            and unit.job == 0
            and unit.target_rank < 0
            and self._hungry_for(unit.work_type)
        ):
            # event-driven like parks: new unmatched inventory reaches the
            # balancer immediately (rate-limited), so a requester parked on
            # ANOTHER server isn't left waiting for the next heartbeat.
            # Only untargeted puts of a type someone is parked for —
            # targeted puts match at the target's home server and never
            # enter snapshots. An O(1) DELTA (just unit metadata), not
            # the O(wq) snapshot walk: at put rates the walk is a
            # measurable GIL tax (the full snapshot still flows on parks,
            # hungry-transitions, and the heartbeat). Units putting
            # faster than the rate limit accumulate and flush as one
            # batched delta (see _send_task_delta).
            self._send_task_delta(unit)
        elif entry is None and type_was_empty:
            # steal-mode dispatch latency: this put flipped a type's
            # advertised inventory from empty to nonempty, and a
            # requester parked on ANOTHER server can only discover it
            # through qmstat — broadcasting now (rate-limited) instead
            # of waiting out the periodic tick turns the trickle p50
            # from gossip-cadence wait into one delivery leg. Peers
            # re-run _try_rfr on every fresh qmstat, so the broadcast
            # alone re-arms their parked entries. Ring mode stays
            # upstream-faithful (interval-only).
            now = time.monotonic()
            if now - self._last_qmstat_event >= self.cfg.qmstat_event_gap:
                self._last_qmstat_event = now
                self._broadcast_qmstat()

    def _on_put_common(self, m: Msg) -> None:
        if self.spill is not None:
            self._maybe_spill(len(m.payload))
        if not self.mem.try_alloc(len(m.payload)):
            self.ep.send(
                m.src,
                msg(Tag.TA_PUT_COMMON_RESP, self.rank, rc=ADLB_PUT_REJECTED,
                    common_seqno=-1),
            )
            return
        seqno = self.cq.put(m.payload)
        if self.wlog is not None:
            self.wlog.log_common_put(seqno, m.payload)
        self._flush_repl()  # write-ahead, like the put ack
        resp = msg(Tag.TA_PUT_COMMON_RESP, self.rank, rc=ADLB_SUCCESS,
                   common_seqno=seqno)
        if self.wal is not None:
            self.wal.defer_ack(m.src, resp)  # durable before acked
            self._flush_wal()
        else:
            self.ep.send(m.src, resp)

    def _on_batch_done(self, m: Msg) -> None:
        cseq = m.common_seqno
        fo = m.data.get("fo_from")
        if fo is not None:
            # rerouted from a failed-over server: translate to the adopted
            # prefix — applying the dead server's seqno untranslated could
            # finalize an UNRELATED local prefix's refcount
            cseq = self._adopted_common_for(fo, cseq)
            if cseq is None:
                return  # prefix lost to replication lag; members' fetches
                #         are counted at _on_get_common
        if self.wlog is not None:
            self.wlog.log_common_refcnt(cseq, m.refcnt)
        self.cq.set_refcnt(cseq, m.refcnt)

    def _on_did_put_at_remote(self, m: Msg) -> None:
        """A targeted put landed off the target's home server; record it and,
        if the target is already parked here, go fetch it (reference
        ``src/adlb.c:2845-2852`` + tq, ``src/xq.h:73-79``)."""
        self.tq.add(m.target_rank, m.work_type, m.server_rank)
        for cand in self.rq.entries():
            if cand.world_rank == m.target_rank and cand.wants(m.work_type):
                self._try_rfr(cand)
                break

    def _on_reserve(self, m: Msg) -> None:
        app = m.src
        rq_id = m.data.get("rqseqno")
        if rq_id is not None:
            # duplicate frame (re-sent across connection churn):
            # processing it again would pin a second unit for the same
            # request. A windowed SEEN-SET, not a monotone high-water
            # mark: with the prefetch pipeline several reserves are in
            # flight, and a reconnect re-send on a NEW connection can be
            # processed before an older frame still queued from the old
            # connection's reader — a max-based filter would discard
            # that never-processed older reserve and leak a stream slot.
            if self._window_seen(self._seen_rqseqnos, app, rq_id):
                return
        self._m_reserves.inc()
        self.stats[InfoKey.NUM_RESERVES] += 1
        # binary-codec clients encode "any type" by omitting the field
        raw_types = m.data.get("req_types")
        req_types = None if raw_types is None else frozenset(raw_types)
        jid = int(m.data.get("job_id", 0) or 0)
        if app in self.local_apps:
            # a reserve names the namespace the rank consumes from —
            # evidence for the per-job exhaustion vote
            self._rank_job[app] = jid
        if self.no_more_work:
            self._reserve_resp(app, ADLB_NO_MORE_WORK, rqseqno=rq_id)
            return
        if self.done_by_exhaustion:
            self._reserve_resp(app, ADLB_DONE_BY_EXHAUSTION, rqseqno=rq_id)
            return
        if jid:
            from adlb_tpu.runtime import jobs as jobsmod

            jstate = self.jobs.ensure(jid).state
            if jstate == jobsmod.DONE:
                self._reserve_resp(app, ADLB_DONE_BY_EXHAUSTION,
                                   rqseqno=rq_id)
                return
            if jstate == jobsmod.KILLED:
                self._reserve_resp(app, ADLB_NO_MORE_WORK, rqseqno=rq_id)
                return
        fetch = bool(m.data.get("fetch", False))
        # clamped: the codec's list element counts are u16, and an
        # unclamped value would make the batch frame unencodable
        fetch_max = min(int(m.data.get("fetch_max", 1) or 1), 4096)
        unit = self.wq.find_match(app, req_types, job=jid)
        if unit is not None:
            self._pin(unit.seqno, app)
            self.activity += 1
            self._job_activity(jid)
            self._n_reserve_immed += 1
            if fetch and fetch_max > 1 and unit.common_len == 0:
                # batched fused fetch: pop up to fetch_max local prefix-free
                # matches into ONE response — the consumer loop's round
                # trips amortize over the batch, and only locally-positioned
                # inventory can batch (remote holders and prefixed units
                # stop the collection), so the mode that pre-positions work
                # locally is the mode that benefits
                units = [unit]
                while len(units) < fetch_max:
                    extra = self.wq.find_match(app, req_types, job=jid)
                    if extra is None or extra.common_len != 0:
                        break
                    self._pin(extra.seqno, app)
                    units.append(extra)
                self._reserve_resp_batch(app, units, rqseqno=rq_id)
                return
            self._reserve_resp(app, ADLB_SUCCESS, unit, fetch=fetch,
                               rqseqno=rq_id)
            return
        if not m.hang:
            self._reserve_resp(app, ADLB_NO_CURRENT_WORK, rqseqno=rq_id)
            return
        self.stats[InfoKey.NUM_RESERVES_PUT_ON_RQ] += 1
        entry = RqEntry(world_rank=app, rqseqno=m.rqseqno,
                        req_types=req_types, fetch=fetch,
                        prefetch=bool(m.data.get("prefetch", False)),
                        job=jid)
        self.rq.add(entry)
        self._rfr_excluded.pop(app, None)
        self._try_rfr(entry)
        if self.cfg.balancer == "tpu" and not self._park_res_local.get(
            app, False
        ):
            # event-driven: a park immediately refreshes this server's
            # requester state at the balancer instead of waiting for the
            # next heartbeat (rate-limited). Reqs-only: the park changed
            # the rq, not the wq, so the O(wq) task walk + fat frame are
            # skipped. Adaptive: skipped entirely for ranks whose last park
            # resolved locally (fine-grained answer economies park per
            # task and are served by local/targeted puts in microseconds —
            # the balancer can't beat that, and the event would be pure
            # GIL tax); a rank the balancer last had to serve remotely
            # keeps the immediate event flow. A misprediction only defers
            # discovery to the heartbeat.
            now = time.monotonic()
            if now - self._last_event_snap >= self.cfg.balancer_min_gap:
                self._last_event_snap = now
                self._send_snapshot(reqs_only=True)

    def _on_stream_idle(self, m: Msg) -> None:
        """The rank's get_work_stream bank ran dry: it is genuinely
        blocked now, so its prefetch reserves become park-eligible for
        exhaustion voting. Any delivery to the rank clears the mark.

        The note carries the client's outstanding reserve ids (slots):
        honoring it only when they exactly match what is parked here
        voids a note that CROSSED a delivery on the wire — the client is
        about to find work in its bank (and may put descendants), so
        marking it idle would open a premature-exhaustion window. The
        client re-announces (1 s cadence) while it stays blocked.

        A rank whose reserves were swept by the rank-death reclaim and
        then resurrected still counts phantom slots no response will
        ever resolve. Those are the claimed ids that are neither parked
        nor in the post-death request window (the window is reset at the
        sweep, so ids the server answered BEFORE the death — responses
        possibly lost with the connection — read as phantom too): each
        is answered with ADLB_RETRY so the stream re-arms it under a
        fresh rqseqno. Claimed ids the server processed after the
        resurrection are deliveries in flight, never re-armed."""
        slots = m.data.get("slots")
        parked_ids = self.rq.ids_for(m.src)
        if m.src in self._swept_streams and slots is not None:
            self._swept_streams.discard(m.src)
            seen = self._seen_rqseqnos.get(m.src)
            seen_ids = seen[0] if seen is not None else ()
            phantom = [i for i in slots
                       if i not in parked_ids and i not in seen_ids]
            for i in phantom:
                self._send_app(
                    m.src,
                    msg(Tag.TA_RESERVE_RESP, self.rank, rc=ADLB_RETRY,
                        rqseqno=i),
                )
            if phantom:
                return  # the re-arms will park; idle re-announces then
        if slots is not None:
            if parked_ids and set(slots) == parked_ids:
                self._stream_idle.add(m.src)
            return
        # legacy count-only note (no slot list): match on count alone
        inflight = m.data.get("inflight")
        if parked_ids and (inflight is None or inflight == len(parked_ids)):
            self._stream_idle.add(m.src)

    def _on_stream_cancel(self, m: Msg) -> None:
        """Drop the rank's prefetch reserves (stream close / finalize).
        Acked so the client can drain deliveries that raced the cancel —
        per-peer FIFO puts any such delivery ahead of this response."""
        self.rq.remove_prefetch(m.src)
        self._stream_idle.discard(m.src)
        self._send_app(
            m.src, msg(Tag.TA_STREAM_CANCEL_RESP, self.rank, rc=ADLB_SUCCESS)
        )

    def _on_get_reserved(self, m: Msg) -> None:
        fo = m.data.get("fo_from")
        if fo is not None:
            # fetch rerouted from a failed-over server: the adopted pin
            # serves under its translated seqno; a consumed-at-death unit
            # (tombstone — its response died with the server) or one lost
            # to replication lag answers ADLB_RETRY (re-reserve), counted
            new = self._adopted_units.get((fo, m.seqno))
            if new is None:
                if (fo, m.seqno, m.src) in self._adopted_fences:
                    # the predecessor fenced this owner's lease before
                    # dying (replicated): a rejected settle, NOT a
                    # counted loss — the re-enqueued unit is live
                    self._send_app(
                        m.src,
                        msg(Tag.TA_GET_RESERVED_RESP, self.rank,
                            rc=ADLB_FENCED),
                    )
                    return
                # once per (dead server, seqno): the promote pass may
                # already have counted it (lost prefix), and a re-sent
                # fetch must not count it twice
                if (fo, m.seqno) not in self._counted_lost:
                    self._counted_lost.add((fo, m.seqno))
                    self._m_failover_lost.inc()
                    self.flight.record(
                        f"failover_lost fetch seqno={m.seqno} from={fo} "
                        f"rank={m.src} "
                        f"tombstoned={(fo, m.seqno) in self._adopted_tombs}"
                    )
                self._send_app(
                    m.src,
                    msg(Tag.TA_GET_RESERVED_RESP, self.rank, rc=ADLB_RETRY),
                )
                return
            m.data["seqno"] = new
        unit = self.wq.get(m.seqno)
        if unit is None or not unit.pinned or unit.pin_rank != m.src:
            cached = self._last_get_resp.get(m.src)
            if cached is not None and cached[0] == m.seqno:
                # duplicate of the fetch we just served (request re-sent
                # across connection churn): the consume is unrepeatable,
                # so replay the cached response instead of raising
                self._send_app(m.src, cached[1])
                return
            if (m.seqno, m.src) in self._fences:
                # the requester's lease on this unit EXPIRED (it went
                # silent past lease_timeout_s) and the unit re-enqueued
                # under a fresh attempt: this late settle is rejected —
                # the fencing half of at-least-once. The client maps
                # ADLB_FENCED onto its ADLB_RETRY path (drop the handle,
                # re-reserve).
                self.flight.record(
                    f"fenced get_reserved seqno={m.seqno} rank={m.src}"
                )
                self._send_app(
                    m.src,
                    msg(Tag.TA_GET_RESERVED_RESP, self.rank,
                        rc=ADLB_FENCED),
                )
                return
            if (
                self.cfg.on_worker_failure == "reclaim"
                and m.src in self._resurrected
            ):
                # the requester was declared dead and came back: its
                # pre-death lease was reclaimed (the unit re-enqueued or
                # already consumed elsewhere), so the handle is void —
                # a retriable code tells it to re-reserve, not to die
                self._send_app(
                    m.src,
                    msg(Tag.TA_GET_RESERVED_RESP, self.rank, rc=ADLB_RETRY),
                )
                return
            if m.seqno in self._killed_units:
                # the unit's job was killed between reserve and fetch:
                # the handle is void and the namespace is closed — the
                # terminal code, not a retry loop
                self._send_app(
                    m.src,
                    msg(Tag.TA_GET_RESERVED_RESP, self.rank,
                        rc=ADLB_NO_MORE_WORK),
                )
                return
            if self._failover:
                # a failover sweep may have unpinned/re-matched this unit
                # (its handoff was routed via a dead home server): the
                # handle is void, not a protocol error — re-reserve
                self.flight.record(
                    f"void handle seqno={m.seqno} rank={m.src} "
                    f"(failover sweep); answering ADLB_RETRY"
                )
                self._send_app(
                    m.src,
                    msg(Tag.TA_GET_RESERVED_RESP, self.rank, rc=ADLB_RETRY),
                )
                return
            # invalid handle — the reference aborts the job here
            # (src/adlb.c:1349-1357)
            raise AdlbError(
                f"server {self.rank}: invalid GET_RESERVED seqno {m.seqno} "
                f"from rank {m.src}"
            )
        # only an HONORED fetch clears a relay marker: a stale replay
        # from a resurrected rank must not erase the at-most-once
        # protection of a live relay to the unit's NEW owner
        self._relay_inflight.pop(m.seqno, None)
        self._consume(unit)
        resp = msg(
            Tag.TA_GET_RESERVED_RESP,
            self.rank,
            rc=ADLB_SUCCESS,
            payload=unit.payload,
            time_on_q=time.monotonic() - unit.time_stamp,
        )
        # at-most-once cache (one response per sender, replaced by its
        # next fetch): a re-sent request replays this instead of raising
        self._last_get_resp[m.src] = (m.seqno, resp)
        delivered = self._send_app(m.src, resp)
        if not delivered:
            self._requeue_consumed(unit)
        elif unit.spans is not None:
            # handle-path fetch served: the terminal hop
            self.journeys.deliver_close(unit)

    def _on_get_common(self, m: Msg) -> None:
        fo = m.data.get("fo_from")
        if fo is not None:
            # fetch rerouted from a failed-over server: translate to the
            # adopted prefix
            new = self._adopted_common_for(fo, m.common_seqno)
            if new is None:
                # prefix lost to replication lag: a counted loss answered
                # with ADLB_RETRY — the consumer discards this member and
                # re-reserves (ADLB_ERROR would read as terminal and the
                # unit would vanish UNcounted, breaking the conservation
                # contract of USERGUIDE §9). Idempotent under re-sends:
                # the same request replayed across churn answers RETRY
                # again without a second count.
                gid = m.data.get("get_id")
                if gid is None or self._last_common.get(m.src) != gid:
                    if gid is not None:
                        self._last_common[m.src] = gid
                    self._m_failover_lost.inc()
                    self.flight.record(
                        f"failover_lost common fo_from={fo} "
                        f"seqno={m.common_seqno} from {m.src}"
                    )
                self._send_app(
                    m.src, msg(Tag.TA_GET_COMMON_RESP, self.rank,
                               rc=ADLB_RETRY, payload=b""),
                )
                return
            m.data["common_seqno"] = new
        get_id = m.data.get("get_id")
        if get_id is not None and self._last_common.get(m.src) == get_id:
            # duplicate of the fetch we just served (matched by request
            # id — the same SEQNO repeats legitimately, one fetch per
            # batch member): re-serve without counting a second get
            # against the refcount; silently drop if GC'd (the original
            # response was already delivered)
            buf = self.cq.peek(m.common_seqno)
            if buf is not None:
                self._send_app(
                    m.src, msg(Tag.TA_GET_COMMON_RESP, self.rank,
                               rc=ADLB_SUCCESS, payload=buf),
                )
            return
        if get_id is not None:
            self._last_common[m.src] = get_id
        if self.wlog is not None:
            self.wlog.log_common_op(
                m.common_seqno, "get", m.src,
                get_id if get_id is not None else -1,
            )
        buf = self.cq.get(m.common_seqno)
        if buf is None:
            # gone: a reclaim double-get outran its credit (narrow race)
            # or an invalid handle — an error response, not a dead server
            from adlb_tpu.types import ADLB_ERROR

            self.flight.record(
                f"get_common miss seqno={m.common_seqno} from {m.src}"
            )
            self._send_app(
                m.src, msg(Tag.TA_GET_COMMON_RESP, self.rank,
                           rc=ADLB_ERROR, payload=b""),
            )
            return
        self._send_app(
            m.src, msg(Tag.TA_GET_COMMON_RESP, self.rank, rc=ADLB_SUCCESS,
                       payload=buf)
        )

    def _on_info_num(self, m: Msg) -> None:
        n, nbytes = self.wq.count_of_type(m.work_type)
        self.ep.send(
            m.src,
            msg(
                Tag.TA_INFO_NUM_RESP,
                self.rank,
                rc=ADLB_SUCCESS,
                count=n,
                nbytes=nbytes,
                max_wq=int(self.stats[InfoKey.MAX_WQ_COUNT]),
            ),
        )

    def _on_info_get(self, m: Msg) -> None:
        """Live Info_get from a client: one stats value from its home server
        (reference ``src/adlb.c:3072-3141``)."""
        try:
            key = InfoKey(m.key)
        except ValueError:
            self.ep.send(
                m.src, msg(Tag.TA_INFO_GET_RESP, self.rank, rc=-1, value=0.0)
            )
            return
        if key is InfoKey.MALLOC_HWM:
            value = float(self.mem.hwm)
        elif key is InfoKey.AVG_TIME_ON_RQ:
            value = self._rq_wait_sum / self._rq_wait_n if self._rq_wait_n else 0.0
        elif key is InfoKey.RSS_KB:
            from adlb_tpu.utils.stats import rss_kb

            value = float(rss_kb())
        elif key is InfoKey.TRANSPORT_BACKLOG:
            value = float(
                self.ep.backlog() if hasattr(self.ep, "backlog") else 0
            )
        else:
            value = float(self.stats.get(key, 0.0))
        self.ep.send(
            m.src,
            msg(Tag.TA_INFO_GET_RESP, self.rank, rc=ADLB_SUCCESS, value=value),
        )

    # ------------------------------------------------------- stealing (pull)

    def _try_rfr(self, entry: RqEntry) -> None:
        """Pick a peer believed to hold matching work and ask it to pin one
        unit for this requester (reference RFR, ``src/adlb.c:1278-1309``)."""
        app = entry.world_rank
        if app in self._rfr_out:
            return
        excluded = self._rfr_excluded.setdefault(app, set())
        # 1) exact directory hit for targeted work parked off-home
        hit = self.tq.lookup(app, entry.req_types)
        if hit is not None and hit[0] not in excluded and hit[0] != self.rank:
            server, wtype = hit
            self._send_rfr(entry, server, targeted_lookup=True, lookup_type=wtype)
            return
        if self.cfg.balancer == "tpu" and \
                0 <= entry.job < self.cfg.balancer_max_jobs:
            return  # untargeted matching is the planner's job — and an
            # outstanding RFR would HIDE this requester from balancer
            # snapshots (the _rfr_out filter), starving the planned
            # path. Only OVERFLOW namespaces (id >= balancer_max_jobs)
            # fall through to the qmstat/RFR pull; in steal mode every
            # job rides it.
        # 2) best advertised priority among peers for the requested types
        best_server, best_prio = -1, ADLB_LOWEST_PRIO
        for s, st in self.peers.items():
            if s == self.rank or s in excluded:
                continue
            if entry.job:
                # per-job inventory gossip: {(job, type): prio} cells
                if entry.req_types is None:
                    cand = [
                        p for (j, _t), p in st.job_hi.items()
                        if j == entry.job
                    ]
                else:
                    cand = [
                        st.job_hi.get((entry.job, t), ADLB_LOWEST_PRIO)
                        for t in entry.req_types
                    ]
                for p in cand:
                    if p > best_prio:
                        best_server, best_prio = s, p
                continue
            types = (
                entry.req_types if entry.req_types is not None else st.hi_prio.keys()
            )
            for t in types:
                p = st.hi_prio.get(t, ADLB_LOWEST_PRIO)
                if p > best_prio:
                    best_server, best_prio = s, p
        if best_server >= 0:
            self._send_rfr(entry, best_server, targeted_lookup=False, lookup_type=-1)

    def _send_rfr(
        self, entry: RqEntry, server: int, targeted_lookup: bool, lookup_type: int
    ) -> None:
        self._rfr_out[entry.world_rank] = time.monotonic()
        self._m_rfrs.inc()
        self.flight.record(
            f"rfr -> server {server} for rank {entry.world_rank} "
            f"(targeted={targeted_lookup})"
        )
        self._send_srv(
            server,
            msg(
                Tag.SS_RFR,
                self.rank,
                for_rank=entry.world_rank,
                rqseqno=entry.rqseqno,
                req_types=None if entry.req_types is None
                else sorted(entry.req_types),
                targeted_lookup=targeted_lookup,
                lookup_type=lookup_type,
                # fused reserve parked here: ask the holder to ship the
                # payload in the RFR response (remote fused fetch) so the
                # requester never pays a GET_RESERVED round trip
                fetch=int(entry.fetch),
                # the requester's namespace: the holder matches only
                # units of this job (omitted/0 = default namespace)
                job_id=entry.job or None,
            ),
        )

    def _rfr_found_resp(
        self, dest: int, for_rank: int, rqseqno: int, unit, fetch: bool
    ) -> None:
        """Pin a matched unit and answer an RFR/plan match toward the
        requester's home server. With ``fetch`` (the park is a fused
        reserve) the payload rides along — remote fused fetch: the home
        server forwards it straight into the TA_RESERVE_RESP and no
        GET_RESERVED leg ever happens. The unit stays PINNED under its
        lease until the home confirms delivery (SS_DELIVERED) or
        compensates (SS_UNRESERVE), so the exhaustion vote and the
        rank-death reclaim see the handoff exactly like a classic pinned
        handoff."""
        self._pin(unit.seqno, for_rank)
        # a handoff is in flight: counts as activity so the exhaustion
        # double-pass cannot declare done around it
        self.activity += 1
        self._job_activity(getattr(unit, "job", 0))
        self._exhaust_held_since = None
        fields = dict(
            found=True,
            for_rank=for_rank,
            rqseqno=rqseqno,
            seqno=unit.seqno,
            work_type=unit.work_type,
            prio=unit.prio,
            target_rank=unit.target_rank,
            work_len=unit.work_len,
            answer_rank=unit.answer_rank,
            common_len=unit.common_len,
            common_server=unit.common_server_rank,
            common_seqno=unit.common_seqno,
        )
        if fetch:
            self._relay_inflight[unit.seqno] = for_rank
            fields.update(
                payload=unit.payload,
                time_on_q=time.monotonic() - unit.time_stamp,
            )
            if unit.spans is not None:
                # the payload leaves with the RFR response: journey
                # custody transfers to the requester's HOME server,
                # which closes it on delivery; our original context is
                # dropped at the SS_DELIVERED consume (an UNRESERVE
                # bounce keeps it — the journey continues here)
                self.journeys.stamp(unit, "relay")
                fields["trace"] = trace_fields(unit)
        if self._send_srv(
            dest, msg(Tag.SS_RFR_RESP, self.rank, **fields)
        ) is None:
            # requester's home died before the response left: undo the
            # pin so the unit stays matchable (like an UNRESERVE)
            self._relay_inflight.pop(unit.seqno, None)
            self.wq.unpin(unit.seqno)
            self.leases.release(unit.seqno)
            if self.wlog is not None:
                self.wlog.log_unpin(unit.seqno)
        elif fetch and self.hedges is not None:
            # defensive: hedge-group members are pinned at launch, so
            # RFR should never relay one — but the payload has now left
            # this server, which IS the commit point for the race. If a
            # member ever does reach here, settle first-wins now rather
            # than let a sibling deliver a second copy.
            self._hedge_settle(unit)

    def _on_rfr(self, m: Msg) -> None:
        req_types = None if m.req_types is None else frozenset(m.req_types)
        jid = int(m.data.get("job_id", 0) or 0)
        unit = self.wq.find_match(m.for_rank, req_types, job=jid)
        if unit is not None:
            self._rfr_found_resp(
                m.src, m.for_rank, m.rqseqno, unit,
                fetch=bool(m.data.get("fetch", False)),
            )
        else:
            self._send_srv(
                m.src,
                msg(
                    Tag.SS_RFR_RESP,
                    self.rank,
                    found=False,
                    for_rank=m.for_rank,
                    rqseqno=m.rqseqno,
                    req_types=m.req_types,
                    targeted_lookup=m.targeted_lookup,
                    lookup_type=m.lookup_type,
                    job_id=jid or None,
                ),
            )

    def _on_rfr_resp(self, m: Msg) -> None:
        app = m.for_rank
        self._rfr_out.pop(app, None)
        if not m.found:
            self._n_rfr_failed += 1
        if m.found:
            entry = self.rq.find_entry(app, m.rqseqno)
            if entry is None or not entry.wants(m.work_type):
                # requester got satisfied (and possibly re-parked with a new
                # request) while the RFR was in flight — compensate
                # (reference SS_UNRESERVE, src/adlb.c:1949-1963). for_rank
                # lets the holder ignore this if the pin already has a new
                # owner (rank-dead reclaim re-matched it). A payload that
                # rode along is simply discarded: the unit is still pinned
                # at the holder, and the UNRESERVE unpins it for re-match.
                self._send_srv(
                    m.src,
                    msg(Tag.SS_UNRESERVE, self.rank, seqno=m.seqno,
                        for_rank=app),
                )
                return
            if m.target_rank >= 0 and app == m.target_rank:
                self.tq.remove(app, m.work_type, m.src)
            self.rq.remove_entry(entry)
            self._stream_idle.discard(app)
            self.rq.demote_rank(app)  # spread scarce inventory (see
            # _satisfy_parked)
            self._park_res_local[app] = False  # RFR/plan = remote delivery
            self._rfr_excluded.pop(app, None)
            wait = time.monotonic() - entry.time_stamp
            self._rq_wait_sum += wait
            self._rq_wait_n += 1
            self.activity += 1
            if "payload" in m.data and entry.fetch:
                # remote fused fetch: the holder shipped the payload in
                # the RFR response — forward it straight into the
                # reservation response (ONE client-visible round trip, no
                # GET_RESERVED leg) and confirm so the holder consumes
                # the pinned unit. Prefixed units carry only their
                # suffix; the client assembles from its prefix cache.
                fields = dict(
                    rc=ADLB_SUCCESS,
                    rqseqno=m.rqseqno,
                    work_type=m.work_type,
                    prio=m.prio,
                    work_len=m.work_len,
                    answer_rank=m.answer_rank,
                    payload=m.payload,
                    time_on_q=m.data.get("time_on_q", 0.0),
                )
                if m.target_rank >= 0:
                    fields["target_rank"] = m.target_rank
                if m.common_len > 0:
                    fields.update(
                        common_len=m.common_len,
                        common_server=m.common_server,
                        common_seqno=m.common_seqno,
                    )
                delivered = self._send_app(
                    app, msg(Tag.TA_RESERVE_RESP, self.rank, **fields)
                )
                tctx = m.data.get("trace")
                if delivered and tctx:
                    # the relayed journey closes HERE: the forwarding is
                    # the delivery, and the deliver hop belongs to this
                    # rank (the holder's copy is dropped at its
                    # SS_DELIVERED consume)
                    spans = list(tctx["spans"])
                    spans.append(("deliver", self.rank, time.monotonic()))
                    spans.append(("finalize", self.rank, time.monotonic()))
                    self.journeys.close_spans(
                        tctx["id"], entry.job, m.work_type, "delivered",
                        spans,
                    )
                self._send_srv(
                    m.src,
                    msg(Tag.SS_DELIVERED, self.rank, seqno=m.seqno,
                        for_rank=app)
                    if delivered
                    else msg(Tag.SS_UNRESERVE, self.rank, seqno=m.seqno,
                             for_rank=app),
                )
                return
            handle = WorkHandle(
                seqno=m.seqno,
                server_rank=m.src,
                common_len=m.common_len,
                common_server_rank=m.common_server,
                common_seqno=m.common_seqno,
            )
            # undeliverable = the requester died since the RFR went out:
            # the remote unit stays pinned under its lease, which the
            # holder's own SS_RANK_DEAD sweep reclaims
            self._send_app(
                app,
                msg(
                    Tag.TA_RESERVE_RESP,
                    self.rank,
                    rc=ADLB_SUCCESS,
                    rqseqno=m.rqseqno,
                    work_type=m.work_type,
                    prio=m.prio,
                    handle=handle.to_ints(),
                    work_len=m.work_len,
                    answer_rank=m.answer_rank,
                ),
            )
        else:
            # stale belief: patch it like the reference patches qmstat
            # (src/adlb.c:1979-2005), strike the peer out for this requester,
            # and retry an alternate candidate.
            jid = int(m.data.get("job_id", 0) or 0)
            if m.targeted_lookup:
                self.tq.remove(app, m.lookup_type, m.src)
            elif jid:
                st = self.peers.get(m.src)
                if st is not None:
                    keys = (
                        [(jid, t) for t in m.req_types]
                        if m.req_types is not None
                        else [k for k in st.job_hi if k[0] == jid]
                    )
                    for k in keys:
                        st.job_hi[k] = ADLB_LOWEST_PRIO
            else:
                st = self.peers.get(m.src)
                if st is not None:
                    types = m.req_types if m.req_types is not None else list(
                        st.hi_prio.keys()
                    )
                    for t in types:
                        st.hi_prio[t] = ADLB_LOWEST_PRIO
            self._rfr_excluded.setdefault(app, set()).add(m.src)
            for cand in self.rq.entries():
                if cand.world_rank == app:
                    self._try_rfr(cand)
                    break

    def _on_unreserve(self, m: Msg) -> None:
        if m.data.get("fo_from") is not None:
            new = self._adopted_unit_for(m)
            if new is None:
                return  # the pin did not survive the takeover
            m.data["seqno"] = new
        unit = self.wq.get(m.seqno)
        if unit is None or not unit.pinned:
            self._relay_inflight.pop(m.seqno, None)
            return
        want = m.data.get("for_rank")
        if want is not None and unit.pin_rank != want:
            # the pin has a NEW owner: the rank-dead sweep already
            # reclaimed and re-matched this unit, so this compensation is
            # stale — honoring it would steal a live rank's reservation
            return
        self._relay_inflight.pop(m.seqno, None)
        if self._hedge_member_unpin(unit):
            # requester handed a racing hedge copy back (shutdown /
            # shrink): retire it rather than re-match a duplicate
            return
        self.wq.unpin(m.seqno)
        self.leases.release(m.seqno)
        if self.wlog is not None:
            self.wlog.log_unpin(m.seqno)
        self._match_rq()

    def _on_delivered(self, m: Msg) -> None:
        """Remote fused fetch confirmation: the home server forwarded our
        payload-carrying RFR response to the requester, so the pinned
        unit is now consumed (the delivery IS the fetch)."""
        if m.data.get("fo_from") is not None:
            new = self._adopted_unit_for(m)
            if new is None:
                return
            m.data["seqno"] = new
        self._relay_inflight.pop(m.seqno, None)
        unit = self.wq.get(m.seqno)
        if unit is None or not unit.pinned or unit.pin_rank != m.for_rank:
            return  # already resolved (reclaim re-match / stale confirm)
        # the home server closed the relayed journey from its copy;
        # drop ours without a second close
        self.journeys.forget(unit)
        self._consume(unit)

    # ------------------------------------------------------- push (memory)

    def _try_push(self) -> None:
        if self._push_offered:
            return  # one outstanding push at a time
        unit = self.wq.find_unpinned()
        if unit is None:
            return
        target = None
        for s, st in self.peers.items():
            if s == self.rank:
                continue
            if (
                s in self._draining_servers
                or s in self._dead_servers
                or not self._is_live_member(s)
            ):
                # elastic membership: a push is custody transfer with no
                # ack — never aim one at a server that is leaving (the
                # drain flushes its wq to the buddy, not frames still in
                # its inbox) or not yet live
                continue
            cap = self.cfg.max_malloc_per_server
            if cap <= 0 or st.nbytes + unit.payload_len <= 0.9 * cap:
                if target is None or st.nbytes < self.peers[target].nbytes:
                    target = s
        if target is None:
            return
        self._push_seq += 1
        qid = (self.rank << 20) | self._push_seq
        self._push_offered[qid] = unit.seqno
        self._m_pushes.inc()
        if self._send_srv(
            target,
            msg(
                Tag.SS_PUSH_QUERY,
                self.rank,
                query_id=qid,
                nbytes=unit.payload_len,
            ),
        ) is None:
            self._push_offered.pop(qid, None)

    def _on_push_query(self, m: Msg) -> None:
        if self._draining_self or self.done:
            # scale-in: no NEW custody once draining — accepted pushes
            # gate the drain's final flush (_maybe_finish_drain), so a
            # query accepted now would only widen that window
            self._send_srv(
                m.src,
                msg(Tag.SS_PUSH_QUERY_RESP, self.rank,
                    query_id=m.query_id, accept=False),
            )
            return
        ok = self.mem.has_room(m.nbytes)
        if ok:
            self.mem.alloc(m.nbytes)  # budget reserved until WORK or DEL
            self._push_reserved[m.query_id] = m.nbytes
        self._send_srv(
            m.src,
            msg(Tag.SS_PUSH_QUERY_RESP, self.rank, query_id=m.query_id,
                accept=ok),
        )

    def _on_push_query_resp(self, m: Msg) -> None:
        seqno = self._push_offered.pop(m.query_id, None)
        if seqno is None:
            return
        unit = self.wq.get(seqno)
        if not m.accept:
            return
        if unit is None or unit.pinned:
            # got reserved while the query was in flight — cancel (reference
            # SS_PUSH_DEL, src/adlb.c:2182-2192)
            self._send_srv(
                m.src, msg(Tag.SS_PUSH_DEL, self.rank, query_id=m.query_id)
            )
            return
        self._unspill(unit)  # shipping needs the bytes
        self.wq.remove(seqno)
        self.mem.free(len(unit.payload))
        if self.wlog is not None:
            self.wlog.log_remove(seqno)
        self.stats[InfoKey.NPUSHED_FROM_HERE] += 1
        if unit.target_rank >= 0:
            home = self.world.home_server(unit.target_rank)
            self._send_srv(
                home,
                msg(
                    Tag.SS_MOVING_TARGETED_WORK,
                    self.rank,
                    app_rank=unit.target_rank,
                    work_type=unit.work_type,
                    from_server=self.rank,
                    to_server=m.src,
                ),
            )
        pushed = dict(
            query_id=m.query_id,
            payload=unit.payload,
            work_type=unit.work_type,
            prio=unit.prio,
            target_rank=unit.target_rank,
            answer_rank=unit.answer_rank,
            home_server=unit.home_server,
            common_len=unit.common_len,
            common_server=unit.common_server_rank,
            common_seqno=unit.common_seqno,
            time_stamp=unit.time_stamp,
            attempts=unit.attempts,
        )
        tf = trace_fields(unit)
        if tf is not None:  # untraced pushes stay byte-identical
            pushed["trace"] = tf
        sent_to = self._send_srv(
            m.src, msg(Tag.SS_PUSH_WORK, self.rank, **pushed)
        )
        if sent_to is None:
            # the accepting peer died before the payload left: a unit
            # already admitted to the system is never dropped — keep it
            self.mem.alloc(len(unit.payload))
            self.wq.add(unit)
            if self.wlog is not None:
                self.wlog.log_put(unit, -1, None)
            self.stats[InfoKey.NPUSHED_FROM_HERE] -= 1
        else:
            # context custody moved with the frame (the receiver adopts)
            self.journeys.forget(unit)

    def _on_push_work(self, m: Msg) -> None:
        self._push_reserved.pop(m.query_id, None)  # budget now owned by the unit
        unit = WorkUnit(
            seqno=self._next_seqno,
            work_type=m.work_type,
            prio=m.prio,
            target_rank=m.target_rank,
            answer_rank=m.answer_rank,
            payload=m.payload,
            home_server=m.home_server,
            common_len=m.common_len,
            common_server_rank=m.common_server,
            common_seqno=m.common_seqno,
            time_stamp=m.time_stamp,
            attempts=int(m.data.get("attempts", 0) or 0),
        )
        self._next_seqno += 1
        tf = m.data.get("trace")
        if tf:
            self.journeys.adopt(unit, tf["id"], tf["spans"], stage="push")
        self.wq.add(unit)
        if self.wlog is not None:
            self.wlog.log_put(unit, -1, None)
        self.stats[InfoKey.NPUSHED_TO_HERE] += 1
        self._match_rq()
        if self._draining_self:
            # the custody this drain was waiting on just landed
            self._maybe_finish_drain()

    def _on_push_del(self, m: Msg) -> None:
        nbytes = self._push_reserved.pop(m.query_id, None)
        if nbytes is not None:
            self.mem.free(nbytes)
        if self._draining_self:
            self._maybe_finish_drain()

    def _on_moving_targeted(self, m: Msg) -> None:
        """Home-server directory fixup when targeted work migrates
        (reference ``src/adlb.c:2071-2108``)."""
        n = int(m.data.get("count", 1) or 1)
        if m.from_server != self.rank:
            self.tq.remove(m.app_rank, m.work_type, m.from_server, n)
        if m.to_server != self.rank:
            self.tq.add(m.app_rank, m.work_type, m.to_server, n)
        # the target may be parked here and able to use it now
        for cand in self.rq.entries():
            if cand.world_rank == m.app_rank and cand.wants(m.work_type):
                self._try_rfr(cand)
                break

    # ------------------------------------------------------- state sync

    def _qmstat_entry(self) -> dict:
        from adlb_tpu.utils.stats import rss_kb

        ent = {
            "nbytes": self.mem.curr,
            "qlen": self.wq.num_unpinned_untargeted(),
            "hi_prio": {t: self.wq.hi_prio_of_type(t) for t in self.world.types},
            # process-level memory truth alongside the accountant's view
            # (the reference feeds its /proc probe into diagnostics the
            # same way, src/adlb.c:3347-3369)
            "rss_kb": rss_kb(),
        }
        jq = self.wq.job_hi_prio()
        if jq:
            # per-job inventory rides along only while job partitions
            # hold work: single-job worlds gossip byte-identically
            ent["jq"] = jq
        if self.world.epoch:
            # elastic membership: the fleet epoch rides the gossip it
            # already pays for, so a server that missed one epoch-bump
            # fan-out (a drain_done toward a peer mid-join, a dropped
            # frame) converges within a tick instead of voiding every
            # exhaustion/END token forever. Static worlds (epoch 0)
            # gossip byte-identically.
            ent["epoch"] = self.world.epoch
        return ent

    def _broadcast_qmstat(self) -> None:
        ent = self._qmstat_entry()
        st = self.peers[self.rank]
        st.nbytes, st.qlen, st.hi_prio = ent["nbytes"], ent["qlen"], ent["hi_prio"]
        st.rss_kb = ent["rss_kb"]
        st.stamp = time.monotonic()
        if self.cfg.qmstat_mode == "ring":
            # reference-faithful store-and-forward ring token: only the
            # master kicks one per interval (reference src/adlb.c:806-822).
            # The token carries the FULL table — each hop installs it,
            # refreshes its own entry, and forwards, so the k-th hop sees
            # everyone else's state k..S hops stale (src/adlb.c:1705-1757).
            if self.is_master and self.world.nservers > 1:
                table = {
                    s: {"nbytes": p.nbytes, "qlen": p.qlen,
                        "hi_prio": dict(p.hi_prio)}
                    for s, p in self.peers.items()
                }
                table[self.rank] = ent
                try:
                    self.ep.send(
                        self._ring_next_live(),
                        msg(Tag.SS_QMSTAT, self.rank,
                            table=table, origin=self.rank,
                            t0=time.monotonic()),
                    )
                except OSError:
                    pass  # droppable token; next interval kicks a fresh one
            return
        for srv in self._live_servers():
            try:
                self.ep.send(srv, msg(Tag.SS_QMSTAT, self.rank, entry=ent))
            except OSError:
                if not self._failover:
                    raise
                self._note_server_unreachable(srv)

    def _apply_qmstat_entry(self, src: int, ent: dict) -> None:
        e = ent.get("epoch")
        if e:
            self.world.note_epoch(e)  # monotonic: only ever heals a lag
        st = self.peers[src]
        st.nbytes = ent["nbytes"]
        st.qlen = ent["qlen"]
        st.hi_prio = dict(ent["hi_prio"])
        st.job_hi = dict(ent.get("jq") or {})
        st.rss_kb = ent.get("rss_kb", 0)
        st.stamp = time.monotonic()
        # fresh evidence of work at this peer lifts any strike-out, else a
        # requester could permanently ignore a peer that refilled later
        if any(p > ADLB_LOWEST_PRIO for p in st.hi_prio.values()) or any(
            p > ADLB_LOWEST_PRIO for p in st.job_hi.values()
        ):
            for excluded in self._rfr_excluded.values():
                excluded.discard(src)

    def _on_qmstat(self, m: Msg) -> None:
        if "table" in m.data:
            # ring token (reference src/adlb.c:1705-1757): install every
            # entry except our own, then refresh ours and forward — unless
            # the token is back at its origin, which records the trip time
            # (reference src/adlb.c:1731-1743)
            for src, ent in m.table.items():
                if src != self.rank:
                    self._apply_qmstat_entry(src, ent)
            if m.origin == self.rank:
                trip = time.monotonic() - m.t0
                self.stats[InfoKey.MAX_QMSTAT_TRIP_TIME] = max(
                    self.stats[InfoKey.MAX_QMSTAT_TRIP_TIME], trip
                )
                n = self._qmstat_trips = getattr(self, "_qmstat_trips", 0) + 1
                avg = self.stats[InfoKey.AVG_QMSTAT_TRIP_TIME]
                self.stats[InfoKey.AVG_QMSTAT_TRIP_TIME] = (
                    avg + (trip - avg) / n
                )
                if trip > self.cfg.qmstat_interval:
                    self.stats[InfoKey.NUM_QMS_EXCEED_INT] += 1
            else:
                m.table[self.rank] = self._qmstat_entry()
                try:
                    self.ep.send(
                        self._ring_next_live(),
                        msg(Tag.SS_QMSTAT, self.rank, table=m.table,
                            origin=m.origin, t0=m.t0),
                    )
                except OSError:
                    pass  # droppable token
        else:
            self._apply_qmstat_entry(m.src, m.entry)
        # fresh knowledge may unblock parked requesters (reference
        # check_remote_work_for_queued_apps after qmstat, src/adlb.c:3536-3579)
        for entry in self.rq.entries():
            if entry.world_rank not in self._rfr_out:
                self._try_rfr(entry)

    # ------------------------------------------------------- balancer (tpu)

    def _send_snapshot(self, reqs_only: bool = False) -> None:
        """Ship queue state to the balancer. ``reqs_only`` skips the O(wq)
        task walk (and the fat task list in the frame) for events that only
        changed the rq — the receiver keeps its previous task view."""
        if reqs_only:
            tasks = None
        else:
            # the full task walk supersedes any pending put deltas (the
            # pending units are in the wq, so the walk carries them)
            self._pending_delta.clear()
            self._delta_deadline = float("inf")
            K = self.cfg.balancer_max_tasks
            snapshot_fast = getattr(self.wq, "snapshot_untargeted", None)
            if snapshot_fast is not None:
                tasks = snapshot_fast(K)  # sorted in C++
            else:
                import heapq as _heapq

                # O(n log K), not a full sort: runs on the reactor thread
                tasks = _heapq.nsmallest(
                    K,
                    (
                        (-u.prio, u.seqno, u.work_type, u.payload_len)
                        for u in self.wq.units()
                        if not u.pinned and u.target_rank < 0
                        and getattr(u, "job", 0) == 0
                    ),
                )
                tasks = [(s, t, -np_, ln) for np_, s, t, ln in tasks]
            tasks = self._merge_job_tasks(tasks, K)
        J = self.cfg.balancer_max_jobs
        reqs = [
            (
                e.world_rank,
                e.rqseqno,
                None if e.req_types is None else sorted(e.req_types),
                # 4th element: fused reserve? drives remote fused fetch
                # on the plan path (3-tuples from native planes read as
                # False — handle delivery, as before). 5th (only when
                # non-zero): the requester's job namespace — the planner
                # only matches within a job, and single-job worlds stay
                # byte-identical on the wire without it.
                bool(e.fetch),
            ) if e.job == 0 else (
                e.world_rank,
                e.rqseqno,
                None if e.req_types is None else sorted(e.req_types),
                bool(e.fetch),
                e.job,
            )
            for e in self.rq.entries()
            if e.world_rank not in self._rfr_out and 0 <= e.job < J
        ][: self.cfg.balancer_max_requesters]
        snap = {
            "tasks": tasks,
            "reqs": reqs,
            "nbytes": self.mem.curr,
            "consumers": len(self.local_apps - self._finalized),
            "stamp": time.monotonic(),
            "mig_acks": dict(self._mig_acks),
        }
        if self.is_master:
            self._accept_snapshot(self.rank, snap)
        else:
            # suppress repeat empty snapshots: an idle server would otherwise
            # wake the master every tick for nothing. An unreported
            # mig_acks change is NOT empty — the ack clears the
            # planner's in-flight credit, and swallowing it would
            # re-open the phantom-credit stall the empty-batch ack
            # exists to close.
            # (reqs-only snapshots do not DELIVER acks — the master
            # inherits the previous task view's acks for them — so they
            # neither satisfy the acks-changed test nor mark the acks
            # as reported)
            empty = (
                not tasks and not reqs
                and (reqs_only or self._mig_acks
                     == getattr(self, "_last_snap_acks", {}))
            )
            if empty and getattr(self, "_last_snap_empty", False):
                return
            self._last_snap_empty = empty
            if not reqs_only:
                self._last_snap_acks = dict(self._mig_acks)
            try:
                self.ep.send(
                    self.world.master_server_rank,
                    msg(Tag.SS_STATE, self.rank, snap=snap),
                )
            except OSError:
                if not self._failover:
                    raise
                self._note_server_unreachable(self.world.master_server_rank)

    def _merge_job_tasks(self, tasks: list, K: int) -> list:
        """Fold non-default namespaces' untargeted inventory into the
        balancer snapshot as 5-tuples carrying the job id (PR 19
        multi-job planning). Job 0 keeps the C++ top-K fast path; the
        other partitions only exist in service mode and are walked in
        Python. The merged list is re-capped at K by EFFECTIVE priority
        (clipped prio + fair-share bias, the planner's own ordering,
        jobdim.weight_bias) so one tenant's flood cannot silently push
        another below the planner's horizon. Identity — and no 5th
        element anywhere — in single-job worlds."""
        J = self.cfg.balancer_max_jobs
        if J <= 1 or not self.wq.has_job_units():
            return tasks
        from adlb_tpu.balancer.jobdim import weight_bias

        extra = []
        for jid in self.wq.job_ids():
            if jid == 0 or not 0 <= jid < J:
                continue  # overflow namespaces keep the qmstat/RFR path
            part = self.wq.part(jid)
            if part is None:
                continue
            for u in part.units():
                if not u.pinned and u.target_rank < 0:
                    extra.append(
                        (u.seqno, u.work_type, u.prio, u.payload_len, jid)
                    )
        if not extra:
            return tasks
        merged = list(tasks) + extra
        if len(merged) > K:
            bias = {
                j: weight_bias(w) for j, w in self.jobs.weights().items()
            }

            def eff(t):
                b = bias.get(t[4], 0) if len(t) > 4 else bias.get(0, 0)
                return max(-(10 ** 9), min(10 ** 9, t[2])) + b

            merged.sort(key=eff, reverse=True)  # stable: ties keep order
            del merged[K:]
        return merged

    def _accept_snapshot(self, src: int, snap: dict) -> None:
        """Master-side snapshot intake, shared by the local and remote
        paths. A reqs-only snapshot (tasks=None) merges with the sender's
        previous task view; stamps are split so a fresh req stamp does not
        re-eligibilize in-flight planned tasks (and vice versa)."""
        prev = self._snapshots.get(src)
        if snap["tasks"] is None:
            snap["tasks"] = prev["tasks"] if prev is not None else []
            snap["task_stamp"] = (
                prev.get("task_stamp", prev["stamp"]) if prev is not None
                else snap["stamp"]
            )
            # the migration-batch acks must stay consistent with the TASK
            # view they ride with: acking a landed batch against a stale
            # task list would clear the credit before the units are
            # visible, re-creating the phantom-top-up chain. When there
            # is NO previous task view at all (first-ever snapshot from
            # this rank is reqs-only), fresh acks would pair with the
            # empty default view above — drop them so the engine falls
            # back to stamp-based clearing until a full view arrives.
            snap["mig_acks"] = (
                prev.get("mig_acks") if prev is not None else None
            )
            # the inherited task list carries its event-delta sequence
            # (the sharded solver keys its fast path on it)
            if prev is not None:
                snap["delta_seq"] = prev.get("delta_seq", 0)
        else:
            snap["task_stamp"] = snap["stamp"]
        self._snapshots[src] = snap
        self._update_parked(src, snap["reqs"])
        self._maybe_wake_balancer(src, snap)

    def _send_task_delta(self, unit) -> None:
        """Event path for new hungry-matched untargeted inventory: ship the
        unit's metadata; the receiver appends it to the sender's last full
        snapshot. Consumed-but-still-listed units are already tolerated
        (plan entries are hints validated at enactment), so a delta
        between full refreshes adds no new race class.

        Units arriving faster than ``balancer_min_gap`` accumulate and
        flush as ONE batched delta the moment the gap elapses: without
        batching, a producer streaming puts at thousands/sec was visible
        to the balancer at one unit per gap — a 30x-lagging inventory
        view that kept the pump's scarcity gate closed while whole worker
        pools idled (the round-3 hotspot startup stall)."""
        # payload bytes, NOT unit.work_len (payload + common prefix): full
        # snapshots record payload bytes, and the planner's admission math
        # compares against payload-only memory accounting (spill-aware:
        # a spilled unit's true size, not its empty resident stub)
        nlen = unit.payload_len
        if self.is_master:
            self._merge_task_delta(
                self.rank, [unit.seqno], [unit.work_type], [unit.prio],
                [nlen], self.mem.curr, jobs=[unit.job],
            )
            return
        self._pending_delta.append(
            (unit.seqno, unit.work_type, unit.prio, nlen, unit.job)
        )
        now = time.monotonic()
        if now - self._last_event_snap >= self.cfg.balancer_min_gap:
            self._flush_task_deltas(now)
        else:
            # schedule the flush for when the gap elapses; the run loop's
            # poll deadline honors it so a burst that STOPS inside the
            # gap still reaches the balancer within one gap
            self._delta_deadline = min(
                self._delta_deadline,
                self._last_event_snap + self.cfg.balancer_min_gap,
            )

    def _flush_task_deltas(self, now: float) -> None:
        self._delta_deadline = float("inf")
        if not self._pending_delta:
            return
        seqnos, wtypes, prios, lens, jobs = zip(*self._pending_delta)
        self._pending_delta.clear()
        self._last_event_snap = now
        extra = {}
        if any(jobs):
            # per-unit namespaces ride only when some unit is non-default
            # — single-job deltas stay byte-identical on the wire
            extra["jobs"] = list(jobs)
        try:
            self.ep.send(
                self.world.master_server_rank,
                msg(
                    Tag.SS_STATE_DELTA,
                    self.rank,
                    seqnos=list(seqnos),
                    work_types=list(wtypes),
                    prios=list(prios),
                    work_lens=list(lens),
                    nbytes=self.mem.curr,
                    **extra,
                ),
            )
        except OSError:
            if not self._failover:
                raise
            self._note_server_unreachable(self.world.master_server_rank)

    def _merge_task_delta(
        self, src: int, seqnos, work_types, prios, work_lens, nbytes: int,
        jobs=None,
    ) -> None:
        snap = self._snapshots.get(src)
        if snap is None:
            return  # no baseline yet; the next full snapshot delivers it
        J = self.cfg.balancer_max_jobs
        room = self.cfg.balancer_max_tasks - len(snap["tasks"])
        for i in range(min(room, len(seqnos))):
            j = int(jobs[i]) if jobs is not None else 0
            if j:
                # same 5th-element rule as full snapshots: job carried
                # only when non-default; overflow namespaces (beyond the
                # planner's job axis) stay off the ledger entirely
                if not 0 <= j < J:
                    continue
                snap["tasks"].append(
                    (seqnos[i], work_types[i], prios[i], work_lens[i], j)
                )
            else:
                snap["tasks"].append(
                    (seqnos[i], work_types[i], prios[i], work_lens[i])
                )
        snap["nbytes"] = nbytes
        # NOTE: snap["stamp"] is NOT bumped — requester (re-)eligibility in
        # the plan ledger must only come from full snapshots that re-observe
        # the requester parked; the new task is eligible under any stamp.
        # The delta SEQUENCE lets the sharded solver's unchanged-server
        # fast path notice the in-place append without a stamp bump
        # (bumping task_stamp here would re-eligibilize planned tasks).
        snap["delta_seq"] = snap.get("delta_seq", 0) + 1
        self._snapshots.bump(src)  # in-place append: version it
        if self._balancer is not None:
            self._balancer.wake.set()

    def _on_state_delta(self, m: Msg) -> None:
        if m.data.get("seqnos") is not None:  # batched (round 4+)
            self._merge_task_delta(
                m.src, m.seqnos, m.work_types, m.prios, m.work_lens,
                m.nbytes, jobs=m.data.get("jobs"),
            )
        else:  # single-unit shape (native daemons predating the batch)
            self._merge_task_delta(
                m.src, [m.seqno], [m.work_type], [m.prio], [m.work_len],
                m.nbytes,
            )

    def _on_state(self, m: Msg) -> None:
        # re-stamp on the master's clock: plan-ledger comparisons must never
        # mix monotonic clocks from different hosts
        m.snap["stamp"] = time.monotonic()
        self._accept_snapshot(m.src, m.snap)

    def _maybe_wake_balancer(self, src: int, snap: dict) -> None:
        """Wake the balancer thread only when a round could plan something
        new: this server's parked-requester set changed (a new park to
        match / a satisfied one to retire), or it reports inventory while
        someone somewhere is parked (the match case for event snapshots).
        A permanently parked requester re-reported in every snapshot (a
        collector of targeted answers, e.g. gfmc's master) must NOT keep
        the round loop spinning — rounds cost real GIL time."""
        if self._balancer is None:
            return
        sig = tuple(sorted((r[0], r[1]) for r in snap["reqs"]))
        changed = sig != self._req_sigs.get(src)
        self._req_sigs[src] = sig
        if changed or (
            snap["tasks"]
            and self._hungry
            and (
                self._hungry_any
                or any(t[1] in self._hungry_types for t in snap["tasks"])
            )
        ):
            self._balancer.wake.set()

    def _update_parked(self, src: int, reqs) -> None:
        """Master bookkeeping of which work types parked requesters want;
        the shared :class:`HungryTracker` decides when the wanted-set
        change is worth broadcasting (growth immediately, shrinks held —
        see adlb_tpu/balancer/hungry.py)."""
        self._broadcast_hungry(self._hungry_tracker.update(src, reqs))

    def _flush_hungry_shrink(self, now: float) -> None:
        self._broadcast_hungry(self._hungry_tracker.flush(now))

    def _broadcast_hungry(self, payload) -> None:
        if payload is None:
            return
        hungry, req_types, grew = payload
        self._hungry = hungry
        self._hungry_any = hungry and req_types is None
        self._hungry_types = frozenset(req_types or ())
        for srv in self._live_servers():
            try:
                self.ep.send(
                    srv,
                    msg(
                        Tag.SS_HUNGRY,
                        self.rank,
                        hungry=int(hungry),
                        # req_types omitted (None) = any-type requester
                        req_types=req_types,
                        grew=int(grew),
                    ),
                )
            except OSError:
                if not self._failover:
                    raise
                self._note_server_unreachable(srv)

    def _hungry_for(self, work_type: int) -> bool:
        return self._hungry and (
            self._hungry_any or work_type in self._hungry_types
        )

    def _on_hungry(self, m: Msg) -> None:
        self._hungry = bool(m.hungry)
        raw = m.data.get("req_types")
        self._hungry_any = self._hungry and raw is None
        self._hungry_types = frozenset(raw or ())
        if self._hungry and m.data.get("grew"):
            # the wanted-set grew: our inventory of the newly wanted types
            # may be heartbeat-stale at the balancer — refresh it now
            self._send_snapshot()

    def _on_plan_match(self, m: Msg) -> None:
        """Enact one plan entry: validate against live state, pin, and hand
        off through the RFR response path (plan staleness compensated exactly
        like RFR races)."""
        if m.data.get("fo_from") is not None:
            return  # plan named the dead server's inventory: stale by
            # construction (the master re-plans from the buddy's snapshot)
        unit = self.wq.get(m.seqno)
        if unit is None or unit.pinned or unit.target_rank >= 0:
            return  # stale plan entry; next round will re-plan
        self._rfr_found_resp(
            m.req_home, m.for_rank, m.rqseqno, unit,
            fetch=bool(m.data.get("fetch", False)),
        )

    def _on_plan_migrate(self, m: Msg) -> None:
        """Planner-directed inventory move: ship the named (still live,
        unpinned, untargeted) units to `dest` so consumers there match
        locally. Demand-driven placement — the planner's generalization of
        the reference's memory-pressure-only push (``src/adlb.c:509-556``)."""
        if m.data.get("fo_from") is not None:
            return  # plan named the dead server's inventory: stale
        units = []
        for seqno in m.seqnos:
            unit = self.wq.get(seqno)
            if unit is None or unit.pinned or unit.target_rank >= 0:
                continue  # stale plan entry
            self._unspill(unit)  # shipping needs the bytes
            self.wq.remove(seqno)
            self.mem.free(len(unit.payload))
            if self.wlog is not None:
                self.wlog.log_remove(seqno)
            self.stats[InfoKey.NPUSHED_FROM_HERE] += 1
            shipped = {
                "payload": unit.payload,
                "work_type": unit.work_type,
                "prio": unit.prio,
                "answer_rank": unit.answer_rank,
                "home_server": unit.home_server,
                "common_len": unit.common_len,
                "common_server": unit.common_server_rank,
                "common_seqno": unit.common_seqno,
                "time_stamp": unit.time_stamp,
                "attempts": unit.attempts,
            }
            if getattr(unit, "job", 0):
                # namespace rides the move (omitted = job 0, so
                # single-job batches stay byte-identical on the wire)
                shipped["job"] = unit.job
            tf = trace_fields(unit)
            if tf is not None:  # untraced batches stay byte-identical
                shipped["trace"] = tf
                self.journeys.forget(unit)  # custody rides the dict
            units.append(shipped)
        if units:
            self.activity += 1
            self._exhaust_held_since = None
        # A fully-stale batch (every unit consumed locally before
        # enactment) must STILL be sent, empty, carrying the planner's
        # batch id: the destination's ack is what clears the planner's
        # in-flight credit, and a silently dropped batch left a phantom
        # credit that suppressed both the solve and the pump for that
        # destination until the TTLs expired — observed as whole worker
        # pools parked ~180 ms mid-run (round 4) while a neighbor held
        # hundreds of units.
        self._send_migrate_batch(
            m.dest, units, bounced=False, mig_id=m.data.get("mig_id", 0)
        )

    def _send_migrate_batch(self, dest: int, units: list, bounced: bool,
                            mig_id: int = 0) -> None:
        """Ship one migration batch, tracked until acked: the units live
        in no wq while serialized in the frame, and a destination dying
        mid-transit must hand them back (see _on_server_dead) instead of
        losing them."""
        self._migrate_unacked += 1
        self._mig_token += 1
        tok = self._mig_token
        sent_to = self._send_srv(
            dest,
            msg(Tag.SS_MIGRATE_WORK, self.rank, units=units, bounced=bounced,
                mig_id=mig_id, mig_tok=tok),
        )
        if sent_to is None:
            # destination (and any buddy route) gone: keep the units
            self._migrate_unacked -= 1
            for u in units:
                self._admit_migrated_unit(u, bounced=bounced)
            return
        self._migrate_pending.setdefault(sent_to, {})[tok] = units

    def _on_migrate_work(self, m: Msg) -> None:
        # ack the planner's batch id via the next snapshot: credits for
        # this source's batches up to this id are now visible in our
        # inventory (bounced resends carry no id — the original sighting
        # already acked it)
        mid = m.data.get("mig_id", 0) or 0
        if mid:
            self._mig_acks[m.src] = max(self._mig_acks.get(m.src, 0), mid)
        bounced_back = []
        for u in m.units:
            # admission control like every other ingress path; a unit already
            # admitted to the system is never dropped, so on a full server it
            # bounces back to the sender once, which then must keep it
            # (overcommit beats losing work)
            if not m.data.get("bounced") and not self.mem.try_alloc(
                len(u["payload"])
            ):
                bounced_back.append(u)
                continue
            if m.data.get("bounced"):
                self.mem.alloc(len(u["payload"]))
            unit = WorkUnit(
                seqno=self._next_seqno,
                work_type=u["work_type"],
                prio=u["prio"],
                target_rank=-1,
                answer_rank=u["answer_rank"],
                payload=u["payload"],
                home_server=u["home_server"],
                common_len=u["common_len"],
                common_server_rank=u["common_server"],
                common_seqno=u["common_seqno"],
                time_stamp=u["time_stamp"],
                attempts=int(u.get("attempts", 0) or 0),
                job=int(u.get("job", 0) or 0),
            )
            self._next_seqno += 1
            tf = u.get("trace")
            if tf:
                self.journeys.adopt(unit, tf["id"], tf["spans"],
                                    stage="migrate")
            self.wq.add(unit)
            if self.wlog is not None:
                self.wlog.log_put(unit, -1, None)
            self.stats[InfoKey.NPUSHED_TO_HERE] += 1
        self._send_srv(
            m.src,
            msg(Tag.SS_MIGRATE_ACK, self.rank,
                mig_tok=m.data.get("mig_tok", 0)),
        )
        if bounced_back:
            self._send_migrate_batch(m.src, bounced_back, bounced=True)
        if m.units:
            self._match_rq()
        if self.cfg.balancer == "tpu" and (m.units or mid):
            # immediate full snapshot: the batch ack and the post-batch
            # inventory reach the planner now, not a heartbeat later —
            # the follow-up top-up cadence rides on this. Sent for EMPTY
            # id-bearing batches too: the ack clearing the phantom
            # credit must not wait for the next heartbeat (and it must
            # ride a FULL snapshot — reqs-only snapshots deliberately
            # inherit the previous acks).
            self._send_snapshot()

    def _on_migrate_ack(self, m: Msg) -> None:
        tok = m.data.get("mig_tok", 0)
        if tok and self._migrate_pending.get(m.src, {}).pop(tok, None) is None:
            # already settled by the dead-destination requeue (the ack
            # raced the death fan-out): decrementing again would wedge
            # the exhaustion vote on a negative unacked count
            return
        self._migrate_unacked -= 1
        held = getattr(self, "_held_checkpoints", None)
        if held and self._migrate_unacked == 0:
            self._held_checkpoints = []
            for h in held:
                self._process_checkpoint(h)

    # ------------------------------------------------------- termination

    def _flush_rq(self, rc: int) -> None:
        # every parked entry — including each slot of a prefetch
        # pipeline — gets its own termination response, so a streaming
        # client can account all its in-flight reserves and drain
        for entry in self.rq.entries():
            self.rq.remove_entry(entry)
            self._reserve_resp(entry.world_rank, rc, rqseqno=entry.rqseqno)
        self._stream_idle.clear()

    def _on_fa_no_more_work(self, m: Msg) -> None:
        if self.no_more_work:
            return
        if self.is_master:
            self._on_ss_no_more_work(m)
        else:
            self.ep.send(
                self.world.master_server_rank, msg(Tag.SS_NO_MORE_WORK, self.rank)
            )

    def _on_ss_no_more_work(self, m: Msg) -> None:
        if self.no_more_work:
            return
        self.no_more_work = True
        if self.is_master:
            for srv in self._live_servers():
                try:
                    self.ep.send(srv, msg(Tag.SS_NO_MORE_WORK, self.rank))
                except OSError:
                    if not self._failover:
                        raise
                    self._note_server_unreachable(srv)
        self._flush_rq(ADLB_NO_MORE_WORK)

    def _all_local_apps_parked(self) -> bool:
        """True when no active local app is off the rq — vacuously true for a
        server with no (remaining) local apps, so worlds where some server
        homes zero apps can still exhaust. A rank whose only parked entries
        are prefetch slots (get_work_stream) counts as parked only once it
        reported FA_STREAM_IDLE: until then the app may be computing a
        banked unit whose descendants could still be put."""
        active = self.local_apps - self._finalized
        return all(
            r in self.rq
            and (self.rq.has_blocking(r) or r in self._stream_idle)
            for r in active
        )

    def _exhaust_vote(self, parked: Optional[list] = None) -> bool:
        """This server's contribution to the exhaustion ring pass.

        Always required: all local apps parked, no pinned units (a pinned
        unit is an in-flight handoff that resolves to a fetch or an
        UNRESERVE), no migration batch in transit. When the token's global
        parked-requester list is available (pass 2), additionally: no unit
        here could satisfy any parked requester anywhere. Unmatchable
        leftovers (e.g. types nobody asks for) deliberately do NOT block —
        matching the reference, which exhausts with work still queued
        (src/adlb.c:754-785) — while work that is still being balanced
        toward a requester, or serialized inside a migration message, does.
        """
        if not self._all_local_apps_parked():
            return False
        if self._migrate_unacked != 0:
            return False
        if self.wq.count != self.wq.num_unpinned():
            return False  # pinned = handoff in flight
        if parked is not None:
            for rank, req_types in parked:
                types = None if req_types is None else frozenset(req_types)
                if self.wq.find_match(rank, types) is not None:
                    return False
        return True

    def _parked_list(self) -> list:
        return [
            (
                e.world_rank,
                None if e.req_types is None else sorted(e.req_types),
            )
            for e in self.rq.entries()
        ]

    def _check_exhaustion(self, now: float) -> None:
        """Master: if every app everywhere might be blocked, run the two-pass
        ring confirmation (reference ``src/adlb.c:754-785,1575-1650``)."""
        if self.no_more_work or self.done_by_exhaustion:
            return
        if self._takeover_pending:
            # succession mid-barrier: a verdict started now could reach
            # a server that has not seen the new epoch yet
            return
        if self.jobs.any_jobs():
            # service mode: once any namespace exists, termination is
            # per-job (_check_job_exhaustion) and the FLEET idles
            # between jobs instead of declaring the world exhausted
            return
        if self._exhaust_inflight:
            # lost-token recovery: if the ring token has not come home in
            # 10 intervals, assume it died and allow a fresh vote; the
            # token id makes any late straggler harmless
            if now - self._exhaust_sent_at < (
                10 * self.cfg.exhaust_check_interval
            ):
                return
            self._exhaust_inflight = False
        if not self._exhaust_vote():
            self._exhaust_held_since = None
            return
        if self._exhaust_held_since is None:
            self._exhaust_held_since = now
            return
        if now - self._exhaust_held_since < self.cfg.exhaust_check_interval:
            return
        self._exhaust_inflight = True
        self._exhaust_sent_at = now
        self._exhaust_token_id += 1
        token = {
            "origin": self.rank,
            "token_id": self._exhaust_token_id,
            "ok": True,
            "act": {self.rank: self.activity},
            "nparked": len(self.rq),
            "parked": self._parked_list(),
            # exhaustion is EPOCH-based, not fixed-count: the verdict is
            # void if membership changed while the token circulated (a
            # rank attaching mid-ring must not race the verdict)
            "epoch": self.world.epoch,
        }
        self._forward_exhaust(Tag.SS_EXHAUST_CHK_1, token)

    def _forward_exhaust(self, tag: Tag, token: dict) -> None:
        self._ring_forward(
            lambda nxt: msg(tag, self.rank, token=token,
                            complete=nxt == token["origin"])
        )

    def _ring_covered(self, visited) -> bool:
        """Origin-side completeness check for ring verdicts. The epoch
        stamp alone cannot catch a hop whose epoch NUMBER healed (qmstat
        gossip / a prior void) while its membership CONTENT still lags —
        `server_live` is the one fan-out without an ack barrier, so such
        a hop's ring_next silently skips the just-published shard. A
        verdict that missed a live server must not conclude; the void
        costs one round while the SS_MEMBER frame lands."""
        need = {
            s for s in self.world.server_ranks
            if s not in self._dead_servers and self._is_live_member(s)
        }
        return need <= set(visited)

    def _on_exhaust_chk(self, m: Msg) -> None:
        if "job" in m.token:
            self._on_job_exhaust_chk(m)
            return
        token = m.token
        phase1 = m.tag is Tag.SS_EXHAUST_CHK_1
        if token.get("epoch", self.world.epoch) != self.world.epoch:
            # the token crossed a membership-epoch boundary (attach /
            # detach / scale / failover): the vote it carries mixes two
            # worlds — void it so the origin re-votes under the new one.
            # note_epoch heals the LAGGING side (a missed bump fan-out);
            # the qmstat gossip heals the other direction, so the void
            # is one round, never forever.
            token["ok"] = False
            self.world.note_epoch(token.get("epoch", 0) or 0)
        if m.data.get("complete") and token["origin"] == self.rank:
            if token.get("token_id", 0) != self._exhaust_token_id:
                return  # straggler from a token we already gave up on
            # token made it all the way around; pass 2 validates against the
            # globally-gathered parked list from pass 1
            visited = token["act"] if phase1 else (
                set(token.get("seen2", ())) | {self.rank}
            )
            ok = (
                token["ok"]
                and token["nparked"] > 0
                and self._exhaust_vote(token["parked"])
                and self.activity == token["act"].get(self.rank, -1)
                and self._ring_covered(token["act"])
                and self._ring_covered(visited)
            )
            if not ok:
                self._exhaust_held_since = None
                self._exhaust_inflight = False
                return
            if phase1:
                token2 = {
                    "origin": self.rank,
                    "token_id": self._exhaust_token_id,
                    "ok": True,
                    "act": token["act"],
                    "nparked": token["nparked"],
                    "parked": token["parked"],
                    "epoch": self.world.epoch,
                }
                self._forward_exhaust(Tag.SS_EXHAUST_CHK_2, token2)
            else:
                self._exhaust_inflight = False
                self._declare_exhaustion()
            return
        # contribute and forward
        if phase1:
            token["ok"] = token["ok"] and self._exhaust_vote()
            token["act"][self.rank] = self.activity
            token["nparked"] = token.get("nparked", 0) + len(self.rq)
            token["parked"] = token.get("parked", []) + self._parked_list()
        else:
            token["ok"] = (
                token["ok"]
                and self._exhaust_vote(token["parked"])
                and self.activity == token["act"].get(self.rank, -1)
            )
            token.setdefault("seen2", []).append(self.rank)
        self._forward_exhaust(m.tag, token)

    def _declare_exhaustion(self) -> None:
        for srv in self._live_servers():
            try:
                self.ep.send(srv, msg(Tag.SS_DONE_BY_EXHAUSTION, self.rank))
            except OSError:
                if not self._failover:
                    raise
                self._note_server_unreachable(srv)
        self._on_done_by_exhaustion(msg(Tag.SS_DONE_BY_EXHAUSTION, self.rank))

    def _on_done_by_exhaustion(self, m: Msg) -> None:
        if self.done_by_exhaustion:
            return
        self.done_by_exhaustion = True
        self.flight.record("done by exhaustion; flushing rq")
        self._flush_rq(ADLB_DONE_BY_EXHAUSTION)

    def _on_local_app_done(self, m: Msg) -> None:
        self._finalized.add(m.src)
        if self.wlog is not None:
            self.wlog.log_app_done(m.src)
        # a finalizing rank can never consume again: any leftover parked
        # entries (an abandoned stream's prefetch slots) must not attract
        # deliveries that would then be consumed into a closed endpoint
        self.rq.remove_rank(m.src)
        self._stream_idle.discard(m.src)
        self._maybe_complete_finalize()

    def _maybe_complete_finalize(self) -> None:
        """Kick or release the END_1 ring once every ACTIVE local app is
        accounted for — by finalizing, or (reclaim policy) by dying.
        Shared by FA_LOCAL_APP_DONE and the rank-death path so a world
        whose last straggler was a casualty still ends cleanly."""
        if not (self._finalized >= self.local_apps):
            return
        if self.is_master and (
            self._member_pending or self._takeover_pending
        ):
            # a membership fan-out or master succession is mid-barrier:
            # kicking the END ring now would stamp an epoch some server
            # has not reached yet. The barrier's completion re-calls this.
            return
        held = getattr(self, "_held_end1", None)
        if self._end1_pending and held is not None:
            self._end1_pending = False
            self._held_end1 = None
            self._forward_end1(held)
        elif self.is_master and not self._end1_pending:
            self._end1_pending = True
            self._forward_end1(
                {"origin": self.rank, "epoch": self.world.epoch}
            )

    def _forward_end1(self, token: dict) -> None:
        self._end1_sent_at = time.monotonic()
        # visit record for the origin's coverage check (every forwarder,
        # origin included at kick)
        seen = token.setdefault("seen", [])
        if self.rank not in seen:
            seen.append(self.rank)
        self._ring_forward(
            lambda nxt: msg(Tag.SS_END_1, self.rank, token=token,
                            complete=(nxt == token["origin"]))
        )

    def _on_end_1(self, m: Msg) -> None:
        self._ending = True
        token = m.token
        tok_epoch = token.get("epoch")
        if tok_epoch is not None and tok_epoch != self.world.epoch:
            # membership changed under the ring (a server retire is the
            # only epoch bump possible here — attach/detach/scale are
            # refused once termination is underway): void the token; the
            # master re-kicks under the new epoch (the retire path, the
            # _periodic lost-END watchdog, and _apply_member all do)
            self.world.note_epoch(tok_epoch)  # heal a lagging view
            if (
                self.is_master
                and not self.done
                and self._finalized >= self.local_apps
            ):
                self._end1_pending = True
                self._forward_end1(
                    {"origin": self.rank, "epoch": self.world.epoch}
                )
            return
        if m.data.get("complete") and token["origin"] == self.rank:
            if not self._ring_covered(token.get("seen", ())):
                # a hop's lagging membership skipped a live server (see
                # _ring_covered): drop the verdict; _end1_pending stays
                # set, so the lost-END watchdog re-kicks once the
                # skipped server's SS_MEMBER frame has landed fleet-wide
                return
            # every server's local apps have finalized: circulate phase 2
            self._ring_forward(
                lambda nxt: msg(Tag.SS_END_2, self.rank, token=token,
                                complete=(nxt == token["origin"]))
            )
            if self._ring_next_live() == self.rank:
                self.done = True
            return
        if self._finalized >= self.local_apps:
            self._forward_end1(token)
        else:
            # hold the token until our apps finish (reference held END_LOOP_1,
            # src/adlb.c:1790-1798)
            self._end1_pending = True
            self._held_end1 = token

    def _on_end_2(self, m: Msg) -> None:
        self._ending = True
        token = m.token
        self.done = True
        if not m.data.get("complete"):
            self._ring_forward(
                lambda nxt: msg(Tag.SS_END_2, self.rank, token=token,
                                complete=(nxt == token["origin"]))
            )

    def _on_peer_eof(self, m: Msg) -> None:
        """A peer's connection closed. Benign during termination; before it,
        a rank died without finalizing — the reference's failure model is
        rank-death-kills-job (``MPI_Abort`` paths, reference
        ``src/adlb.c:2508-2526``), and the alternative here is a silent
        world hang. Detection is connection-based: a rank that dies before
        ever sending a frame leaves no connection to EOF, and only the
        launch harness's timeout (or the watchdog, for servers) catches
        it."""
        lost_local_app = (
            self.world.is_app(m.src)
            and m.src in self.local_apps
            and m.src not in self._finalized
        )
        if self.done or self._aborted:
            return
        if self.world.is_server(m.src):
            # server peers get the dedicated path: abort (reference
            # semantics), or failover when the policy allows — including
            # mid-termination, where the death is suspected first (a
            # finished peer's exit also EOFs)
            self._on_server_eof(m.src)
            return
        if self.no_more_work or self.done_by_exhaustion or self._ending:
            # termination underway: peer EOFs are normally benign — but a
            # LOCAL app dying unfinalized would hold the END_1 ring
            # forever. Under "reclaim" the death accounting releases it;
            # under "abort" this stays the reference's behaviour (the
            # harness timeout catches it).
            if lost_local_app and self.cfg.on_worker_failure == "reclaim":
                self._declare_rank_dead(m.src)
            return
        if lost_local_app:
            # only the HOME server judges an app EOF: finalize knowledge is
            # home-local, and a finished app legitimately EOFs at every
            # other server it ever fetched from
            if self.cfg.on_worker_failure == "reclaim":
                aprintf(
                    True, self.rank,
                    f"app rank {m.src} connection lost before finalize; "
                    f"reclaiming its work (on_worker_failure=reclaim)",
                )
                self._declare_rank_dead(m.src)
                return
            aprintf(
                True, self.rank,
                f"app rank {m.src} connection lost before finalize; "
                f"aborting the world (reference rank-failure semantics)",
            )
            self._do_abort(-3, broadcast=True)

    # ------------------------------------------------- gray failures
    # Lease expiry with fencing + retry budgets + dead-letter quarantine
    # (no reference analogue; Config(lease_timeout_s) / max_unit_retries,
    # both inert by default). PR 2/PR 4 survive CLEAN deaths — an EOF
    # fans out the reclaim — but a worker that HANGS without dying
    # (SIGSTOP, wedged accelerator, live-but-frozen VM) holds its leases
    # forever and never EOFs. Here: a lease whose owner has been silent
    # past the timeout is FENCED (the lease_id becomes a fencing token —
    # late settles from the old owner answer ADLB_FENCED) and its unit
    # re-enqueues under a fresh attempt; a rank silent for 2x the
    # timeout is declared hung by its HOME server (rank-dead under
    # "reclaim", abort under "abort"); and a unit whose attempts exceed
    # the retry budget moves to the dead-letter quarantine instead of
    # serially killing the fleet.

    def _scan_leases(self, now: float) -> None:
        timeout = self.cfg.lease_timeout_s
        # native (C) clients have no heartbeat plane: a compute-bound
        # rank is indistinguishable from a hung one, so binary peers
        # keep reference semantics — their leases never expire and they
        # are never declared hung (libadlb would otherwise be aborted
        # mid-computation by its own liveness watchdog)
        native = getattr(self.ep, "binary_peers", None) or ()
        expired = 0
        for lease in self.leases.leases():
            if lease.owner in self._dead_ranks:
                continue  # the rank-dead sweep owns those
            if lease.owner in native:
                continue
            t0 = max(
                lease.granted_at,
                lease.renewed_at,
                self._last_heard.get(lease.owner, 0.0),
            )
            if now - t0 <= timeout:
                continue
            self._expire_lease(lease, now)
            expired += 1
        if expired:
            # reclaimed inventory is activity (an in-flight exhaustion
            # vote must not conclude around it) and may satisfy parked
            # requesters right now
            self.activity += 1
            self._exhaust_held_since = None
            self._match_rq()
        # hang detection: only the HOME server judges (finalize knowledge
        # is home-local, exactly like the EOF path) — total silence past
        # 2x the timeout is a gray-failed rank. Per-lease expiry above
        # already freed its work at ~1x; this releases its termination
        # accounting so the WORLD still completes around it.
        for r in sorted(self.local_apps):
            if r in self._dead_ranks or r in self._finalized:
                continue
            if r in native:
                continue  # no heartbeat plane: busy, not hung
            last = self._last_heard.get(r)
            if last is None:
                continue  # never heard from: startup grace
            silent = now - last
            if silent <= 2.0 * timeout:
                continue
            if self.cfg.on_worker_failure == "reclaim":
                aprintf(
                    True, self.rank,
                    f"app rank {r} silent {silent:.2f}s "
                    f"(lease_timeout_s={timeout}); declaring it hung "
                    f"(on_worker_failure=reclaim)",
                )
                self.flight.record(
                    f"rank_hung rank={r} silent_s={silent:.3f}"
                )
                self._declare_rank_dead(r)
            else:
                aprintf(
                    True, self.rank,
                    f"app rank {r} silent {silent:.2f}s; aborting the "
                    f"world (on_worker_failure=abort)",
                )
                self.flight.record(
                    f"rank_hung rank={r} silent_s={silent:.3f} (abort)"
                )
                self._do_abort(-3, broadcast=True)
                return

    def _expire_lease(self, lease, now: float) -> None:
        """Fence one expired lease and return its unit to service.

        At-least-once by design: the owner may be slow rather than dead
        — it may already hold (or be receiving) the payload — so the
        re-enqueued unit can execute twice. The fence guarantees the
        narrow thing that must never happen: the old owner double-
        SETTLING the unit (its late fetch answers ADLB_FENCED and the
        stale-relay/unreserve guards ignore it)."""
        seqno, owner = lease.seqno, lease.owner
        self.leases.release(seqno)
        self._add_fence(seqno, owner)
        self._m_leases_expired.inc()
        # owner-labelled expiry counter: the lease OWNER (the stalled
        # app rank) otherwise appears only in this server's flight ring
        # — the SLO incident bundles window-delta this cell to name the
        # suspect rank directly
        self.metrics.counter("leases_expired_by", owner=str(owner)).inc()
        if self.wlog is not None:
            self.wlog.log_fence(seqno, owner)
        self.flight.record(
            f"lease_expired seqno={seqno} owner={owner} "
            f"lease_id={lease.lease_id} "
            f"age_s={now - max(lease.granted_at, lease.renewed_at):.3f}"
        )
        unit = self.wq.get(seqno)
        if unit is None or not unit.pinned or unit.pin_rank != owner:
            return  # already resolved through another path
        # a relay in flight toward the silent owner: unlike the rank-DEAD
        # sweep (at-most-once: the owner is gone, consume), expiry keeps
        # the unit — the documented at-least-once window
        self._relay_inflight.pop(seqno, None)
        if self._hedge_member_unpin(unit):
            # a hedge sibling still races for this unit's logical put:
            # this copy retires instead of re-enqueueing (the fence
            # above already bars the silent owner)
            return
        self.wq.unpin(seqno)
        if unit.spans is not None:
            self.journeys.stamp(unit, "expire")
        if self.wlog is not None:
            self.wlog.log_unpin(seqno)
        quarantined = self._bump_attempts(unit, in_wq=True)
        if unit.common_seqno >= 0 and not quarantined:
            # the silent owner may have fetched the prefix already; the
            # re-consumption fetches it again (bounded-leak direction,
            # as in the rank-death sweep). On quarantine: NO common op.
            # A credit expects a re-consumption that will never come
            # (certain leak); a forfeit assumes the silent owner never
            # fetched — if it did, the overshoot could GC the prefix
            # out from under surviving members. With neither, the books
            # close exactly when every epoch fetched and leak bounded
            # otherwise (the targeted-drop path forfeits only because
            # its suffix-only delivery PROVES the share unaccounted).
            self._forfeit_common(
                unit.common_seqno, unit.common_server_rank, op="credit"
            )

    def _add_fence(self, seqno: int, owner: int) -> None:
        key = (seqno, owner)
        if key in self._fences:
            return
        self._fences.add(key)
        self._fence_order.append(key)
        if len(self._fence_order) > 65536:  # bounded, like tombstones
            self._fences.discard(self._fence_order.popleft())

    # ------------------------------------------------------- tail hedging
    # Config(hedge_budget_frac) > 0 (runtime/hedge.py holds the pure
    # bookkeeping; this section owns every queue/lease/WAL side effect).
    # A straggling leased-but-unfetched unit — age past the live
    # per-(job, type) p99 the master gossips, or its holder showing the
    # PR 16 stall signature — gets a hedge SIBLING minted and handed
    # directly to an already-parked requester on a DIFFERENT rank. The
    # sibling is pinned at launch and never sits unpinned in the queue,
    # so migration/push can never move it off-home and the whole race
    # settles on this reactor. First terminal wins (_hedge_settle, from
    # _consume / _quarantine_unit / the relay-send site); every losing
    # sibling is fenced through the (seqno, owner) machinery and
    # removed — its late fetch answers ADLB_FENCED exactly like an
    # expired-lease owner's. Members that lose their pin WITHOUT
    # terminating (expiry / unreserve / rank-death) retire instead of
    # re-enqueueing while a sibling still races; the LAST live copy
    # always re-enters service, so work is never lost to hedging.

    def _scan_hedges(self, now: float) -> None:
        """Walk the lease table for stragglers worth hedging. Rare-path
        cost: gated on the hedge budget being configured, cadenced well
        inside the age floor."""
        thr_map = self.journeys.tail_thr
        suspects = self._hedge_suspects(now)
        min_age_s = self.cfg.hedge_min_age_ms / 1e3
        hm = self.hedges
        for lease in list(self.leases.leases()):
            seqno, owner = lease.seqno, lease.owner
            if owner in self._dead_ranks:
                continue  # the rank-dead sweep owns those
            if hm.is_member(seqno) or hm.is_vetoed(seqno):
                continue
            if seqno in self._relay_inflight:
                continue  # payload already committed cross-server
            unit = self.wq.get(seqno)
            if unit is None or not unit.pinned or unit.pin_rank != owner:
                continue
            if unit.target_rank >= 0 or unit.common_seqno >= 0:
                # targeted work may not run elsewhere; a fused batch
                # member shares prefix books a duplicate would corrupt
                continue
            if unit.spilled:
                continue  # payload not resident (defensive: pins unspill)
            thr = thr_map.get((unit.job, unit.work_type))
            if should_hedge(now - unit.time_stamp, thr,
                            owner in suspects, min_age_s):
                self._try_hedge(unit, owner, now,
                                why="thr" if thr is not None
                                and now - unit.time_stamp > thr
                                else "suspect")

    def _hedge_suspects(self, now: float) -> set:
        """Stall signatures feeding the trigger — the PR 16 heuristic
        (obs/slo.py suspect_ranks) over THIS server's scan window:
        in-window growth of the owner-labelled lease-expiry cells, plus
        (master only) gossip-stale members under the /healthz rule."""
        from adlb_tpu.obs.slo import suspect_ranks

        cur = self.metrics.labelled("leases_expired_by")
        memo = self._hedge_expiry_memo
        deltas = {k: v - memo.get(k, 0) for k, v in cur.items()}
        self._hedge_expiry_memo = cur
        stale = []
        if self.is_master and self._obs_sync_armed and self._fleet_seen:
            cut = 3.0 * self.cfg.obs_sync_interval
            stale = [r for r, (_seq, at) in self._fleet_seen.items()
                     if now - at > cut]
        # the expiry-growth evidence is a point event (non-zero in
        # exactly the one scan window that straddles it) but the stall
        # it names persists — hold the suspicion for a lease-timeout so
        # a rank that just expired one lease hedges its NEXT straggler
        # promptly instead of only during a single 1/4-floor window
        hold = max(self.cfg.lease_timeout_s,
                   4.0 * self.cfg.hedge_min_age_ms / 1e3)
        for r in suspect_ranks(stale, (), deltas):
            self._hedge_suspect_until[r] = now + hold
        for r in [r for r, t in self._hedge_suspect_until.items()
                  if t <= now]:
            del self._hedge_suspect_until[r]
        return set(self._hedge_suspect_until)

    def _try_hedge(self, unit, owner: int, now: float, why: str) -> None:
        """Launch one hedge sibling for ``unit`` — or veto. Veto order
        matters: backpressure signals veto STICKILY (overload is exactly
        when a later retry would start a storm); an empty budget or no
        parked taker only defers to a later scan."""
        hm = self.hedges
        seqno = unit.seqno
        plen = len(unit.payload)
        job = self.jobs.get(unit.job) if unit.job else None
        over_quota = False
        if job is not None and job.quota_bytes > 0:
            part = self.wq.part(unit.job)
            used = part.total_bytes if part is not None else 0
            over_quota = used + plen > job.quota_bytes
        if self.mem.under_pressure or over_quota:
            hm.veto(seqno)
            self.metrics.counter("hedges_vetoed",
                                 reason="backpressure").inc()
            self.flight.record(
                f"hedge_vetoed seqno={seqno} reason=backpressure "
                f"(pressure={self.mem.under_pressure} quota={over_quota})"
            )
            return
        if not hm.try_debit(unit.job):
            self.metrics.counter("hedges_vetoed", reason="budget").inc()
            return  # transient: deliveries refill the bucket
        # a hedge only launches INTO an already-parked requester on a
        # different, live rank — no taker means no launch (the sibling
        # must pin immediately; it never sits unpinned in open matching)
        entry = None
        for e in self.rq.entries():
            if e.world_rank == owner or e.world_rank in self._dead_ranks:
                continue
            if e.job != unit.job or not e.wants(unit.work_type):
                continue
            entry = e
            break
        if entry is None:
            hm.refund(unit.job)
            self.metrics.counter("hedges_vetoed", reason="no_taker").inc()
            return
        if not self.mem.try_alloc(plen):
            hm.refund(unit.job)
            hm.veto(seqno)  # allocation failure IS backpressure
            self.metrics.counter("hedges_vetoed",
                                 reason="backpressure").inc()
            return
        sib = WorkUnit(
            seqno=self._next_seqno,
            work_type=unit.work_type,
            prio=unit.prio,
            target_rank=-1,
            answer_rank=unit.answer_rank,
            payload=unit.payload,
            home_server=self.rank,
            attempts=unit.attempts,
            job=unit.job,
        )
        self._next_seqno += 1
        hm.open(seqno, sib.seqno, unit.job)
        self._m_hedges_launched.inc()
        if unit.spans is not None:
            # the origin stamps the hedge hop FIRST, then the sibling's
            # journey starts as a copy of that history under its own
            # (tail-minted) id — whichever copy terminates, the
            # promoted journey shows the race (why=["hedged"])
            self.journeys.stamp(unit, "hedge")
            self.journeys.adopt(
                sib, self.journeys.mint_tail_id(), list(unit.spans)
            )
        elif self.journeys.tail:
            self.journeys.begin_tail(sib, now)
            self.journeys.stamp(sib, "hedge")
        self.wq.add(sib)
        if self.wlog is not None:
            self.wlog.log_put(sib, -1, None)
            self.wlog.log_hedge(sib.seqno, seqno)
        self.flight.record(
            f"hedge_launched origin={seqno} sib={sib.seqno} owner={owner} "
            f"taker={entry.world_rank} why={why} "
            f"age_s={now - unit.time_stamp:.3f}"
        )
        # a launch is activity: an in-flight exhaustion vote must not
        # conclude around the race (the fused delivery below settles it
        # synchronously anyway; the handle path keeps it open)
        self.activity += 1
        self._job_activity(unit.job)
        self._exhaust_held_since = None
        self._pin(sib.seqno, entry.world_rank)
        self._satisfy_parked(entry, sib, local=False)

    def _hedge_settle(self, unit) -> None:
        """First terminal among a hedge group's members: close the race
        exactly once, BEFORE the winner's own settle proceeds — every
        other live member is fenced against its pin owner (the loser's
        late fetch answers ADLB_FENCED through the PR 5 check) and
        removed from service, on this reactor, so no second payload can
        ever leave the books."""
        hm = self.hedges
        if hm is None:
            return
        res = hm.settle(unit.seqno)
        if res is None:
            return
        origin, losers = res
        if unit.seqno != origin:
            self._m_hedges_won.inc()
        removed = 0
        for s in losers:
            u = self.wq.get(s)
            if u is None:
                continue
            if u.pinned:
                self._relay_inflight.pop(s, None)
                self.leases.release(s)
                self._add_fence(s, u.pin_rank)
                if self.wlog is not None:
                    self.wlog.log_fence(s, u.pin_rank)
            self._m_hedges_fenced.inc()
            self._unspill(u)
            self.wq.remove(s)
            self.mem.free(len(u.payload))
            if self.wlog is not None:
                self.wlog.log_remove(s)
            # the loser's journey is released, never closed: the winner
            # carries the hedge hop, and a loser fold would double the
            # unit in every latency estimator
            self.journeys.forget(u)
            removed += 1
            self.flight.record(
                f"hedge_fenced loser={s} winner={unit.seqno} "
                f"origin={origin}"
            )
        if removed:
            self.activity += 1  # inventory changed under the vote

    def _hedge_member_unpin(self, unit) -> bool:
        """An open hedge-group member lost its pin WITHOUT terminating
        (lease expiry / unreserve compensation / rank-death reclaim).
        While a sibling still races, re-enqueueing this copy would put
        two live duplicates into open matching with nobody left to
        fence the loser — so it retires (fenced against its old owner,
        removed, forgotten). Returns True when the caller must skip its
        normal requeue. The LAST live copy returns False and re-enters
        service through the caller's standard path: hedging never loses
        work."""
        hm = self.hedges
        if hm is None:
            return False
        siblings = hm.survivors_of(unit.seqno)
        if not any(self.wq.get(s) is not None for s in siblings):
            if siblings:
                # the race is over with this copy the survivor: dissolve
                # the group and supersede the sibling's OP_HEDGE mark so
                # recovery adopts it like any ordinary unit
                hm.drop(unit.seqno)
                self._hedge_relog(unit)
            return False
        hm.drop(unit.seqno)
        self.leases.release(unit.seqno)
        if unit.pinned and (unit.seqno, unit.pin_rank) not in self._fences:
            self._add_fence(unit.seqno, unit.pin_rank)
            if self.wlog is not None:
                self.wlog.log_fence(unit.seqno, unit.pin_rank)
        self._unspill(unit)
        self.wq.remove(unit.seqno)
        self.mem.free(len(unit.payload))
        if self.wlog is not None:
            self.wlog.log_remove(unit.seqno)
        self.journeys.forget(unit)
        self.flight.record(
            f"hedge_member_retired seqno={unit.seqno} "
            f"(sibling still racing)"
        )
        # whoever survives the race may need to dissolve too: if the
        # retirement left exactly one member, it is an ordinary unit now
        for s in siblings:
            if not hm.survivors_of(s):
                u = self.wq.get(s)
                if u is not None:
                    self._hedge_relog(u)
                break
        return True

    def _hedge_relog(self, unit) -> None:
        """A hedge race dissolved with ``unit`` the sole survivor:
        re-log its OP_PUT so the mirror/WAL's OP_HEDGE mark is
        superseded — recovery must adopt the survivor as an ordinary
        unit, not discard it as a speculative sibling."""
        if self.wlog is not None:
            self.wlog.log_put(unit, -1, None)

    def _bump_attempts(self, unit, in_wq: bool) -> bool:
        """Account one failed delivery attempt; quarantine the unit when
        it exceeds the retry budget. Returns True when quarantined.
        ``in_wq``: whether the unit currently sits (unpinned) in the wq
        — False on the consumed-but-undeliverable path."""
        unit.attempts += 1
        if self.wlog is not None and in_wq:
            self.wlog.log_attempts(unit.seqno, unit.attempts)
        maxr = self.cfg.max_unit_retries
        if maxr <= 0 or unit.attempts <= maxr:
            return False
        self._quarantine_unit(unit, in_wq=in_wq)
        return True

    def _quarantine_record(self, unit) -> dict:
        """Dead-letter record for one unit — the single source of the
        record shape (see _quarantine_unit / _adopt_quarantined). A
        fused batch member carries only its suffix: reattach the prefix
        when this server stores it, so the operator retrieves the
        payload the app would have received; when the prefix lives
        elsewhere the record is flagged ``suffix_only`` and keeps the
        common handle instead of silently passing off the suffix as
        the whole payload."""
        payload, suffix_only = unit.payload, False
        cseq, cs = unit.common_seqno, unit.common_server_rank
        clen = unit.common_len
        if cseq >= 0:
            prefix = self.cq.peek(cseq) if cs in (-1, self.rank) else None
            if prefix is not None:
                payload, cseq, cs, clen = prefix + payload, -1, -1, 0
            else:
                suffix_only = True
        return {
            "seqno": unit.seqno,
            "work_type": unit.work_type,
            "prio": unit.prio,
            "target_rank": unit.target_rank,
            "answer_rank": unit.answer_rank,
            "payload": payload,
            "attempts": unit.attempts,
            "server_rank": self.rank,
            "suffix_only": suffix_only,
            "common_seqno": cseq,
            "common_server_rank": cs,
            "common_len": clen,
        }

    def _quarantine_unit(self, unit, in_wq: bool) -> None:
        """Move a unit to the dead-letter store: out of the wq (settled
        for exhaustion voting — termination never hangs on a poison
        unit), counted exactly-once, payload retained for retrieval."""
        # quarantine is a terminal: it must close any hedge race (and
        # fence the siblings) exactly like a delivery would — without
        # it, a poisoned origin would leave its sibling racing a unit
        # the books already settled. No budget credit: only deliveries
        # fund the bucket.
        self._hedge_settle(unit)
        self._unspill(unit)  # the dead-letter record keeps the payload
        if in_wq:
            self.wq.remove(unit.seqno)
            self.leases.release(unit.seqno)
            self.mem.free(len(unit.payload))
        if self.wlog is not None:
            if not in_wq:
                # the mirror tombstoned this unit at consume; re-install
                # it so the quarantine entry has something to move
                self.wlog.log_put(unit, -1, None)
            self.wlog.log_quarantine(unit.seqno)
        self.quarantine.append(self._quarantine_record(unit))
        self.stats[InfoKey.QUARANTINED] += 1
        self._m_quarantined.inc()
        if unit.spans is not None:
            # quarantine is terminal: close the journey with its cause
            self.journeys.close(unit, "quarantined")
        self.flight.record(
            f"unit_quarantined seqno={unit.seqno} type={unit.work_type} "
            f"attempts={unit.attempts}"
        )

    def _adopt_quarantined(self, f: dict, old_seqno: int,
                           dead: int) -> None:
        """Take over a failed-over predecessor's dead-letter entry under
        a fresh local seqno, re-counting it here (the dead server's own
        QUARANTINED stat died with it — exactly-once holds because only
        the survivor's count reaches the final aggregation). A fused
        member's prefix handle translates through the adopted-commons
        map first, so the record can reattach a prefix this buddy now
        stores."""
        cs = f.get("common_server_rank", -1)
        cseq = f.get("common_seqno", -1)
        if cseq >= 0 and cs == dead:
            new_c = self._adopted_commons.get((dead, cseq))
            if new_c is not None:
                cs, cseq = self.rank, new_c
            # else: prefix lost to replication lag — the stale handle
            # stays in the record, honestly suffix_only
        unit = WorkUnit(
            seqno=self._next_seqno,
            work_type=f["work_type"],
            prio=f["prio"],
            target_rank=f["target_rank"],
            answer_rank=f["answer_rank"],
            payload=f["payload"],
            common_len=f.get("common_len", 0),
            common_server_rank=cs,
            common_seqno=cseq,
            attempts=f.get("attempts", 0),
        )
        self._next_seqno += 1
        self.quarantine.append(self._quarantine_record(unit))
        self.stats[InfoKey.QUARANTINED] += 1
        self._m_quarantined.inc()
        if self.wlog is not None:
            self.wlog.log_put(unit, -1, None)
            self.wlog.log_quarantine(unit.seqno)
        self.flight.record(
            f"unit_quarantined seqno={unit.seqno} (adopted, was "
            f"{old_seqno})"
        )

    def _peer_has_room(self, nbytes: int) -> bool:
        """Any live peer believed able to admit nbytes under its cap —
        the backpressure eligibility test (a push/hint would help)."""
        cap = self.cfg.max_malloc_per_server
        if cap <= 0:
            return True
        for s, st in self.peers.items():
            if s == self.rank or s in self._dead_servers:
                continue
            if st.nbytes + nbytes <= cap:
                return True
        return False

    def _on_heartbeat(self, m: Msg) -> None:
        """Liveness beacon (last-heard already stamped in _handle); with
        a seqno it is an explicit lease extension (ctx.extend_lease). A
        seqno whose lease is gone (expired/consumed) is silently stale —
        the owner's next settle attempt learns through the normal
        fence/retry paths."""
        self._m_heartbeats.inc()
        seqno = m.data.get("seqno")
        if seqno is not None:
            fo = m.data.get("fo_from")
            if fo is not None:
                seqno = self._adopted_units.get((fo, seqno))
                if seqno is None:
                    return
            lease = self.leases.get(seqno)
            if lease is not None and lease.owner == m.src:
                self.leases.renew(seqno)

    def _on_get_quarantined(self, m: Msg) -> None:
        """Dead-letter retrieval: this server's quarantine store, shipped
        as parallel per-unit lists (the codec's batch idiom — plain dicts
        do not cross the TCP fabric); the client zips them back into
        records."""
        q = list(self.quarantine)
        self._send_app(
            m.src,
            msg(
                Tag.TA_QUARANTINED_RESP,
                self.rank,
                rc=ADLB_SUCCESS,
                seqnos=[r["seqno"] for r in q],
                work_types=[r["work_type"] for r in q],
                prios=[r["prio"] for r in q],
                target_ranks=[r["target_rank"] for r in q],
                answer_ranks=[r["answer_rank"] for r in q],
                attempts_list=[r["attempts"] for r in q],
                payloads=[r["payload"] for r in q],
                suffix_onlys=[
                    1 if r.get("suffix_only") else 0 for r in q
                ],
            ),
        )

    # ------------------------------------------------- service mode
    # Durable multi-tenant operation (ROADMAP item 3): the per-server
    # WAL (Config(wal_dir), runtime/wal.py) makes the pool survive
    # process death, and job namespaces (runtime/jobs.py) multiplex
    # many jobs over one persistent fleet — per-job wq partitions,
    # per-job exhaustion rings, per-tenant put quotas, and a /jobs
    # control plane on the ops endpoint + the FA_JOB_CTL round trip.

    def _refresh_wlog(self) -> None:
        """Rebuild the single mutation-log handle (network replication
        log, WAL, tee of both, or None) — called at init and whenever
        the replication stream re-targets."""
        repl = getattr(self, "repl", None)
        wal = getattr(self, "wal", None)
        if repl is not None and wal is not None:
            from adlb_tpu.runtime.wal import TeeLog

            self.wlog = TeeLog([repl, wal])
        else:
            self.wlog = repl if repl is not None else wal

    def _flush_wal(self, force: bool = False) -> None:
        """Write buffered WAL entries; run the group commit when due and
        release the put acks it covers."""
        w = self.wal
        if w is None:
            return
        prof = self._prof_shared
        if prof is not None:
            prof.set_phase("wal_fsync")
        synced_before = w.syncs
        self._release_wal_acks(w.tick(time.monotonic(), force=force))
        if w.syncs != synced_before:
            self._m_wal_syncs.inc(w.syncs - synced_before)

    def _release_wal_acks(self, acks) -> None:
        """Send the put acks a group commit (or compaction) released;
        traced puts among them get their ``wal_commit`` span — the ack
        release IS the durability instant the client observes."""
        for app, resp in acks:
            if self._trace_wal_pending:
                unit = self._trace_wal_pending.pop(
                    (app, resp.data.get("put_id")), None
                )
                if unit is not None and unit.spans is not None:
                    self.journeys.stamp(unit, "wal_commit")
                    # the OP_TRACE written at put time predates this
                    # span: re-log so the durable copy (and the buddy's
                    # mirror) carries the commit hop too
                    if self.wlog is not None:
                        self.wlog.log_trace(unit.seqno, unit.trace_id,
                                            unit.spans)
            self._send_app(app, resp)

    def _wal_seed(self, log) -> None:
        """Durable non-pool state re-seeded into a fresh WAL segment at
        compaction (the ACK2 shard carries the pool itself): quarantine
        records, put-dedup windows, and the job table."""
        from adlb_tpu.runtime.jobs import STATE_CODES

        for q in self.quarantine:
            unit = WorkUnit(
                seqno=q["seqno"], work_type=q["work_type"], prio=q["prio"],
                target_rank=q["target_rank"], answer_rank=q["answer_rank"],
                payload=q["payload"], attempts=q["attempts"],
                common_len=q.get("common_len", 0),
                common_server_rank=q.get("common_server_rank", -1),
                common_seqno=q.get("common_seqno", -1),
            )
            log.log_put(unit, -1, None)
            log.log_quarantine(q["seqno"])
        for src, (_ids, order) in self._seen_puts.items():
            log.log_seen_puts(src, order)
        for job in self.jobs.values():
            if job.job_id:
                log.log_job(job.job_id, STATE_CODES[job.state],
                            job.quota_bytes, job.name)
        # live units' trace contexts: the ACK2 shard cannot carry them,
        # so they re-seed as OP_TRACE entries applied after the manifest
        # installs the units
        for u in self.wq.units():
            if u.trace_id and u.spans is not None:
                log.log_trace(u.seqno, u.trace_id, u.spans)
        # open hedge races: each live sibling's OP_HEDGE mark must
        # survive compaction (the fresh segment re-logs the sibling's
        # OP_PUT above, which would otherwise launder it into an
        # ordinary unit and recovery would adopt BOTH copies)
        if self.hedges is not None:
            for sib, origin in self.hedges.live_siblings():
                if self.wq.get(sib) is not None:
                    log.log_hedge(sib, origin)

    def _recover_from_wal(self) -> None:
        """Cold restart: replay the on-disk log (snapshot shard + tail)
        through a ReplicaMirror and adopt the result into the live
        queues. Units come back unpinned — their owners died with the
        previous fleet — so recovered work re-executes, the standard
        crash-recovery contract; an ACKED put is always here (or in the
        quarantine), never silently gone."""
        mirror = self.wal.recover()
        if mirror is None:
            return
        n_units = 0  # adopted: units, commons, quarantine, job table
        hedge_dropped = 0
        for seqno in sorted(mirror.units):
            if seqno in mirror.hedges:
                # live hedge SIBLING at crash time: a speculative copy
                # of an origin that also recovers — adopting both would
                # hand two live duplicates to a restarted world with the
                # group state gone. Discard the sibling; the origin
                # re-enqueues, re-execution falls inside the documented
                # lease-expiry at-least-once window. (A sibling that WON
                # its race was superseded by OP_CONSUME, and one that
                # survived a dissolved race by a fresh OP_PUT.)
                hedge_dropped += 1
                continue
            f = dict(mirror.units[seqno])
            payload = f.pop("payload")
            trace_id = f.pop("trace_id", 0)
            tspans = f.pop("spans", None)
            unit = WorkUnit(seqno=seqno, payload=payload,
                            home_server=self.rank, **f)
            unit.pinned = False
            unit.pin_rank = -1
            self.mem.alloc(len(payload))
            if trace_id:
                # cold restart keeps the journey: the pre-crash spans
                # (durable via OP_TRACE / the compaction seed) continue
                # with a "replay" hop
                self.journeys.adopt(unit, trace_id, tspans,
                                    stage="replay")
            self.wq.add(unit)
            # re-log toward the buddy only (self.repl): the WAL already
            # holds these entries durably — re-teeing them would double
            # the segment on every restart
            if self.repl is not None:
                self.repl.log_put(unit, -1, None)
            self._next_seqno = max(self._next_seqno, seqno + 1)
            n_units += 1
        for seqno in sorted(mirror.commons):
            buf, refcnt, ngets, _credits = mirror.commons[seqno]
            self.mem.alloc(len(buf))
            self.cq.restore(seqno, refcnt, ngets, buf)
            if self.repl is not None:
                self.repl.log_common_put(seqno, buf)
                self.repl.log_common_state(seqno, refcnt, ngets, 0)
        for seqno in sorted(mirror.quarantined):
            f = mirror.quarantined[seqno]
            unit = WorkUnit(
                seqno=seqno, work_type=f["work_type"], prio=f["prio"],
                target_rank=f["target_rank"], answer_rank=f["answer_rank"],
                payload=f["payload"], attempts=f.get("attempts", 0),
                common_len=f.get("common_len", 0),
                common_server_rank=f.get("common_server_rank", -1),
                common_seqno=f.get("common_seqno", -1),
            )
            self.quarantine.append(self._quarantine_record(unit))
            self.stats[InfoKey.QUARANTINED] += 1
            self._next_seqno = max(self._next_seqno, seqno + 1)
            if self.repl is not None:
                self.repl.log_put(unit, -1, None)
                self.repl.log_quarantine(seqno)
        # mirror.seen_puts is deliberately NOT adopted: the put-dedup
        # window keys on per-client put ids, and a cold restart means
        # NEW client processes whose ids restart from 1 — a restored
        # window would silently swallow their first puts as "duplicates"
        # of the dead world's. (The failover promote path DOES adopt it:
        # there the clients survive and their id streams continue.)
        for jid, (code, quota, name) in mirror.jobs_meta.items():
            self.jobs.restore(jid, code, quota, name)
        self.wal_recovered = n_units
        if n_units or mirror.entries_applied:
            self.flight.record(
                f"wal_recovered units={n_units} "
                f"commons={len(mirror.commons)} "
                f"quarantined={len(mirror.quarantined)} "
                f"jobs={len(mirror.jobs_meta)} "
                f"hedge_siblings_dropped={hedge_dropped} "
                f"torn_tail={self.wal.recovered_torn}"
            )
            aprintf(
                self.cfg.aprintf_flag, self.rank,
                f"WAL recovery: {n_units} units, {len(mirror.commons)} "
                f"common entries, {len(mirror.quarantined)} quarantined, "
                f"{len(mirror.jobs_meta)} jobs "
                f"(torn tail: {self.wal.recovered_torn})",
            )

    def _void_killed_unit(self, seqno: int) -> None:
        self._killed_units.add(seqno)
        self._killed_order.append(seqno)
        if len(self._killed_order) > 65536:
            self._killed_units.discard(self._killed_order.popleft())

    # -- job control plane ---------------------------------------------------

    def ctl_request(self, req: dict, timeout: float = 5.0) -> dict:
        """Thread-safe control-plane injection (the ops HTTP thread's
        POST /jobs): enqueue for the reactor, wait for its verdict."""
        req = dict(req)
        req["done"] = threading.Event()
        self._ctl_inbox.append(req)
        if not req["done"].wait(timeout):
            raise TimeoutError("reactor did not service the control "
                               "request in time")
        if "error" in req:
            raise RuntimeError(req["error"])
        return req["result"]

    def _drain_ctl_inbox(self) -> None:
        while self._ctl_inbox:
            req = self._ctl_inbox.popleft()
            try:
                req["result"] = self._handle_ctl(req)
            except Exception as e:  # noqa: BLE001 — surfaces over HTTP
                req["error"] = repr(e)
            req["done"].set()

    def _handle_ctl(self, req: dict) -> dict:
        op = req["op"]
        if op == "submit":
            jid = self._alloc_job_id()
            self._job_ctl_fanout(
                "submit", jid, name=str(req.get("name", "")),
                quota=int(req.get("quota_bytes", 0) or 0),
            )
            return {"job_id": jid, "state": self.jobs.get(jid).state}
        if op in ("drain", "kill"):
            jid = int(req["job_id"])
            if self.jobs.get(jid) is None:
                raise KeyError(f"unknown job {jid}")
            self._job_ctl_fanout(op, jid)
            return {"job_id": jid, "state": self.jobs.get(jid).state}
        if op == "update":
            # POST /jobs/<id>: live policy tweak — fair-share weight
            # and/or quota (0 = leave unchanged, -1 = unlimited)
            jid = int(req["job_id"])
            if self.jobs.get(jid) is None:
                raise KeyError(f"unknown job {jid}")
            weight = req.get("weight")
            if weight is not None:
                weight = float(weight)
                if not weight > 0.0:
                    raise ValueError("weight must be > 0")
            self._job_ctl_fanout(
                "update", jid,
                quota=int(req.get("quota_bytes", 0) or 0),
                weight=weight,
            )
            return self.jobs.get(jid).summary()
        if op == "fleet":
            return self.fleet_doc()
        if op == "scale_out":
            if not self.is_master:
                raise ValueError("scale_out is a master op")
            if self._member_terminating():
                raise RuntimeError("world terminating")
            return self._request_scale_out("manual")
        if op == "scale_in":
            if not self.is_master:
                raise ValueError("scale_in is a master op")
            if self.cfg.on_server_failure != "failover":
                raise RuntimeError(
                    "scale_in drains through the promote path: "
                    "on_server_failure='failover' required (clients "
                    "must follow TA_HOME_TAKEOVER)"
                )
            if self._member_terminating():
                raise RuntimeError("world terminating")
            live = [
                s for s in self.world.server_ranks
                if s not in self._dead_servers
                and s not in self._draining_servers
                and self._is_live_member(s)
            ]
            rank = req.get("rank")
            if rank is None:
                # newest scale-out shard first, else the highest-ranked
                # non-master base server
                extras = [s for s in live
                          if s not in self.world.spec.server_ranks]
                cands = extras or [
                    s for s in live
                    if s != self.world.master_server_rank
                ]
                if not cands:
                    raise RuntimeError("no drainable server")
                rank = max(cands)
            rank = int(rank)
            if rank == self.world.master_server_rank:
                raise ValueError("cannot drain the master")
            if rank not in live:
                raise ValueError(f"server {rank} is not live")
            if len(live) <= 2:
                raise RuntimeError(
                    "refusing to drain below two live servers (the "
                    "drained shard needs a buddy)"
                )
            epoch = self.world.epoch + 1
            for s in self._live_servers():
                try:
                    self.ep.send(
                        s, msg(Tag.SS_MEMBER, self.rank,
                               mop="server_drain", rank=rank,
                               epoch=epoch),
                    )
                except OSError:
                    self._note_server_unreachable(s)
            self._apply_member(
                dict(mop="server_drain", rank=rank, epoch=epoch)
            )
            return {"rank": rank, "epoch": epoch}
        if op == "slo":
            # POST /slo: add an objective to the live engine (creating
            # it on first use). Master-only — evaluation runs where the
            # merged fleet view lives.
            if not self.is_master:
                raise RuntimeError("slo objectives live on the master")
            if not self._obs_sync_armed:
                raise RuntimeError(
                    "slo needs the obs plane (ops_port + "
                    "obs_sync_interval > 0)"
                )
            from adlb_tpu.obs.slo import SloEngine

            if self._slo_engine is None:
                self._slo_engine = SloEngine(
                    self.cfg.slo_eval_interval
                    or self.cfg.obs_sync_interval
                )
            o = self._slo_engine.add(req.get("objective") or {})
            self.flight.record(f"slo_objective_added {o['name']}")
            if self._failover and self.repl is not None:
                # live-POSTed objectives are brain state: without this
                # the promoted deputy's /slo would silently forget them
                self.repl.log_slo(dict(o))
            return {"objective": o,
                    "n_objectives": len(self._slo_engine.objectives)}
        if op == "control":
            # POST /control: live policy tweak on the fleet controller
            # (thresholds, bounds, cooldown, dry_run) — no restart
            if not self.is_master:
                raise RuntimeError("the controller lives on the master")
            if self._controller is None:
                raise RuntimeError(
                    "controller not configured (Config(control=True))"
                )
            pol = self._controller.update_policy(
                req.get("policy") or {}
            )
            self.flight.record(
                "control_policy_updated "
                + " ".join(f"{k}={v}" for k, v in sorted(pol.items()))
            )
            if self._failover and self.repl is not None:
                self.repl.log_control(dict(pol))
            return {"policy": pol}
        raise ValueError(f"unknown control op {op!r}")

    def _alloc_job_id(self) -> int:
        """Master: next unused job id — floored above every id the table
        has ever seen, so ids restored from the WAL (or adopted in a
        takeover) are never reissued to a new tenant (a reused id would
        inherit the old job's state: a DONE job is born closed, a
        RUNNING one silently merges two tenants)."""
        jid = max(self._job_next_id, self.jobs.max_id() + 1)
        self._job_next_id = jid + 1
        return jid

    def _job_ctl_fanout(self, op: str, jid: int, name: str = "",
                        quota: int = 0,
                        weight: Optional[float] = None) -> None:
        """Master: apply a job lifecycle change and broadcast it."""
        for srv in self._live_servers():
            if srv == self.rank:
                continue
            try:
                self.ep.send(
                    srv,
                    msg(Tag.SS_JOB_CTL, self.rank, op=op, job_id=jid,
                        job_name=name, quota=quota, weight=weight),
                )
            except OSError:
                if not self._failover:
                    raise
                self._note_server_unreachable(srv)
        self._apply_job_ctl(op, jid, name, quota, weight)

    def _on_ss_job_ctl(self, m: Msg) -> None:
        self._apply_job_ctl(
            m.data["op"], m.job_id, m.data.get("job_name", ""),
            m.data.get("quota", 0), m.data.get("weight"),
        )

    def _apply_job_ctl(self, op: str, jid: int, name: str = "",
                       quota: int = 0,
                       weight: Optional[float] = None) -> None:
        from adlb_tpu.runtime.jobs import STATE_CODES

        if weight is None and op == "submit" and self.cfg.job_weights:
            # Config(job_weights) pre-names ids the allocator will hand
            # out: stamp the weight onto the Job at birth so later
            # weights() fan-outs (and /jobs summaries) carry it
            weight = self.cfg.job_weights.get(jid)
        job = self.jobs.apply(op, jid, name=name, quota_bytes=quota,
                              weight=weight)
        if weight is not None:
            # hand the new fair-share map to the balancer thread; it
            # applies set_job_weights() at its next round top (the
            # engine's caches are not safe to flush from the reactor)
            self._pending_job_weights = self._effective_job_weights()
            if self._balancer is not None:
                self._balancer.wake.set()
            self.flight.record(
                f"job_weight job={jid} weight={job.weight:g}"
            )
            if self.is_master and self._failover and self.repl is not None:
                # fair-share weights don't ride wlog.log_job (state/
                # quota/name only): stream them so a promoted deputy's
                # planner starts from the live weight map
                self.repl.log_job_weight(jid, job.weight)
        if self.wlog is not None:
            self.wlog.log_job(jid, STATE_CODES[job.state],
                              job.quota_bytes, job.name)
        if op == "done":
            self._m_jobs_done.inc()
            self.flight.record(f"job_done job={jid}")
            self._flush_rq_job(jid, ADLB_DONE_BY_EXHAUSTION)
        elif op == "kill":
            dropped = self.wq.drop_job(jid)
            for u in dropped:
                self._spill_drop(u)
                self.mem.free(len(u.payload))
                self.leases.release(u.seqno)
                self._relay_inflight.pop(u.seqno, None)
                self._void_killed_unit(u.seqno)
                if u.spans is not None:
                    # a kill is terminal for the journey too (and must
                    # release the recorder's live slot — leaking it
                    # would eventually cap out tracing fleet-wide)
                    self.journeys.close(u, "dropped")
                if u.common_seqno >= 0:
                    # a fused batch member's prefix share will never be
                    # fetched: forfeit it so the common entry still GCs
                    # (same discipline as every other drop path)
                    self._forfeit_common(u.common_seqno,
                                         u.common_server_rank)
                if self.wlog is not None:
                    self.wlog.log_remove(u.seqno)
            self.flight.record(
                f"job_killed job={jid} dropped={len(dropped)}"
            )
            self._flush_rq_job(jid, ADLB_NO_MORE_WORK)

    def _effective_job_weights(self) -> dict:
        """Config(job_weights) as the base layer (ids the allocator may
        not have issued yet), overridden by every job the table actually
        knows — including explicit resets back to neutral."""
        w = dict(self.cfg.job_weights or {})
        for j in self.jobs.values():
            if j.weight != 1.0:
                w[j.job_id] = j.weight
            else:
                w.pop(j.job_id, None)
        return w

    def _on_fa_job_ctl(self, m: Msg) -> None:
        op = m.data["op"]
        jid = int(m.data.get("job_id", 0) or 0)
        if op == "attach":
            # the rank's HOME server records the namespace binding; the
            # per-job exhaustion vote reads it for this server's locals
            self._rank_job[m.src] = jid
            if jid:
                self.jobs.ensure(jid)
            self._send_app(
                m.src,
                msg(Tag.TA_JOB_CTL_RESP, self.rank, rc=ADLB_SUCCESS,
                    job_id=jid),
            )
            return
        if op == "status":
            job = self.jobs.get(jid)
            self._send_app(
                m.src,
                msg(Tag.TA_JOB_CTL_RESP, self.rank,
                    rc=ADLB_SUCCESS if job is not None else -1,
                    job_id=jid,
                    status=None if job is None else job.summary()),
            )
            return
        if not self.is_master:
            # submit/drain/kill are the master's to serialize (it
            # allocates ids and owns the fan-out)
            self._send_app(
                m.src,
                msg(Tag.TA_JOB_CTL_RESP, self.rank, rc=-1, job_id=jid),
            )
            return
        if op == "submit":
            jid = self._alloc_job_id()
            name = m.data.get("job_name", "")
            if isinstance(name, bytes):
                name = name.decode("utf-8", "replace")
            self._job_ctl_fanout(
                "submit", jid, name=name,
                quota=int(m.data.get("quota", 0) or 0),
            )
        elif op in ("drain", "kill"):
            if self.jobs.get(jid) is None:
                self._send_app(
                    m.src,
                    msg(Tag.TA_JOB_CTL_RESP, self.rank, rc=-1, job_id=jid),
                )
                return
            self._job_ctl_fanout(op, jid)
        else:
            self._send_app(
                m.src,
                msg(Tag.TA_JOB_CTL_RESP, self.rank, rc=-1, job_id=jid),
            )
            return
        self._send_app(
            m.src,
            msg(Tag.TA_JOB_CTL_RESP, self.rank, rc=ADLB_SUCCESS,
                job_id=jid),
        )

    # -- per-job termination -------------------------------------------------

    def _flush_rq_job(self, jid: int, rc: int) -> None:
        """Flush ONE job's parked requesters (its termination verdict)
        without touching any other namespace — one job draining never
        blocks another."""
        for entry in self.rq.entries():
            if entry.job == jid:
                self.rq.remove_entry(entry)
                self._reserve_resp(entry.world_rank, rc,
                                   rqseqno=entry.rqseqno)

    def _exhaust_vote_job(self, jid: int) -> bool:
        """This server's per-job exhaustion vote: the job's partition is
        EMPTY here (consumed work only — a job completes when its queue
        drains; unmatchable leftovers keep it running until /jobs kill)
        and every local app attached to the job is parked or finished.
        Ranks attached to other namespaces are invisible — their compute
        never blocks this job's verdict."""
        part = self.wq.part(jid)
        if part is not None and part.count != 0:
            return False
        for r in self.local_apps:
            if r in self._finalized or r in self._dead_ranks:
                continue
            if self._rank_job.get(r, 0) != jid:
                continue
            if not (
                r in self.rq
                and (self.rq.has_blocking(r) or r in self._stream_idle)
            ):
                return False
        return True

    def _check_job_exhaustion(self, now: float) -> None:
        """Master: the WORLD exhaustion logic run per live job — same
        held-vote debounce, same two-pass ring with activity stamps,
        token stamped with the job id."""
        if self.no_more_work or self.done_by_exhaustion:
            return
        for jid in self.jobs.active_ids():
            job = self.jobs.get(jid)
            if job.exhaust_inflight:
                if now - job.exhaust_sent_at < (
                    10 * self.cfg.exhaust_check_interval
                ):
                    continue
                job.exhaust_inflight = False  # lost-token recovery
            if not self._exhaust_vote_job(jid):
                job.exhaust_held_since = None
                continue
            if job.exhaust_held_since is None:
                job.exhaust_held_since = now
                continue
            if now - job.exhaust_held_since < (
                self.cfg.exhaust_check_interval
            ):
                continue
            job.exhaust_inflight = True
            job.exhaust_sent_at = now
            job.exhaust_token_id += 1
            token = {
                "job": jid,
                "origin": self.rank,
                "token_id": job.exhaust_token_id,
                "ok": True,
                "act": {self.rank: job.activity},
                "epoch": self.world.epoch,
            }
            self._forward_exhaust(Tag.SS_EXHAUST_CHK_1, token)

    def _on_job_exhaust_chk(self, m: Msg) -> None:
        token = m.token
        jid = token["job"]
        phase1 = m.tag is Tag.SS_EXHAUST_CHK_1
        job = self.jobs.ensure(jid)
        if token.get("epoch", self.world.epoch) != self.world.epoch:
            # per-job votes key on the membership epoch exactly like the
            # world vote: a rank joining (and attaching to this job)
            # mid-ring voids the verdict (and heals a lagging view)
            token["ok"] = False
            self.world.note_epoch(token.get("epoch", 0) or 0)
        if m.data.get("complete") and token["origin"] == self.rank:
            if token.get("token_id", 0) != job.exhaust_token_id:
                return  # straggler from an abandoned token
            from adlb_tpu.runtime import jobs as jobsmod

            ok = (
                token["ok"]
                and self._exhaust_vote_job(jid)
                and job.activity == token["act"].get(self.rank, -1)
                # same completeness bar as the world vote: a hop whose
                # membership lagged a scale-out shard skipped it
                and self._ring_covered(token["act"])
                # a submitted-but-never-started job must not complete:
                # "done" needs evidence the job RAN (activity somewhere
                # in the fleet) — or an explicit drain, which is the
                # operator saying there is nothing more to wait for
                and (
                    sum(token["act"].values()) > 0
                    or job.state == jobsmod.DRAINING
                )
            )
            if not ok:
                job.exhaust_held_since = None
                job.exhaust_inflight = False
                return
            if phase1:
                token2 = {
                    "job": jid,
                    "origin": self.rank,
                    "token_id": job.exhaust_token_id,
                    "ok": True,
                    "act": token["act"],
                    "epoch": self.world.epoch,
                }
                self._forward_exhaust(Tag.SS_EXHAUST_CHK_2, token2)
            else:
                job.exhaust_inflight = False
                self._job_ctl_fanout("done", jid)
            return
        # contribute and forward
        if phase1:
            token["ok"] = token["ok"] and self._exhaust_vote_job(jid)
            token["act"][self.rank] = job.activity
        else:
            token["ok"] = (
                token["ok"]
                and self._exhaust_vote_job(jid)
                and job.activity == token["act"].get(self.rank, -1)
            )
        self._forward_exhaust(m.tag, token)

    def _job_activity(self, jid: int) -> None:
        if jid:
            self.jobs.ensure(jid).activity += 1

    # ------------------------------------------------- elastic membership
    # adlb_tpu/runtime/membership.py; no reference analogue — upstream
    # fixes every role at ADLB_Init. The MASTER owns allocation (rank
    # ids, home servers, fleet epochs) and the fan-out/ack barrier;
    # every server applies SS_MEMBER ops against its MemberView; the
    # exhaustion/END rings key on the epoch, so a join can never race a
    # termination verdict; scale-out bootstraps a new shard from a
    # donor over the acked migration plane; scale-in drains through the
    # failover promote path with a force-flushed full mirror (zero
    # counted losses).

    @staticmethod
    def _mstr(v) -> str:
        return v.decode("utf-8", "replace") if isinstance(v, bytes) else v

    def _member_terminating(self) -> bool:
        return (
            self.no_more_work or self.done_by_exhaustion or self._ending
            or self._end1_pending or self.done or self._aborted
        )

    def _is_live_member(self, s: int) -> bool:
        """A server eligible for rings/fan-outs/buddy duty: base servers
        always (death is handled by _dead_servers); scale-out shards
        only once their reactor announced ready (server_live fan-out) —
        a not-yet-running shard must not receive ring tokens or become
        someone's replication target."""
        if s in self.world.spec.server_ranks or s == self.rank:
            return True
        return s in self._member_live

    def _buddy_excluded(self) -> set:
        """Servers a buddy walk must skip: the dead, plus joined-but-
        not-yet-live shards (no mirror could exist there)."""
        out = set(self._dead_servers)
        for s in self.world.extra_servers:
            if not self._is_live_member(s):
                out.add(s)
        return out

    def _on_fa_member(self, m: Msg) -> None:
        mop = self._mstr(m.data.get("mop") or "")
        if mop == "detach":
            self._member_detach_req(m)
            return
        if mop != "attach":
            self._member_refuse(m.src, f"unknown member op {mop!r}")
            return
        if not self.is_master:
            self._member_refuse(m.src, "attach goes to the master server")
            return
        if self._member_terminating():
            self._member_refuse(
                m.src, "world terminating", rc=ADLB_NO_MORE_WORK
            )
            return
        kind = self._mstr(m.data.get("kind") or "app")
        host = m.data.get("host")
        port = m.data.get("port")
        addr = (self._mstr(host), int(port)) if host is not None else None
        if addr is not None and hasattr(self.ep, "addr_map"):
            # the joiner's listener: the reply (and everyone's future
            # traffic) dials it; learned under the PROVISIONAL id too so
            # the TA_MEMBER_RESP can be delivered at all
            self.ep.addr_map.setdefault(m.src, addr)
        rank = self._member_next_rank
        self._member_next_rank += 1
        epoch = self.world.epoch + 1
        if addr is not None:
            self._member_addrs[rank] = addr
        if kind == "server":
            fields = dict(mop="server_join", rank=rank, epoch=epoch)
            resp = dict(
                rc=ADLB_SUCCESS, rank=rank, epoch=epoch,
                member=None,  # filled at reply time (fresh snapshot)
                jobs=self._member_jobs_seed(),
                # the new shard must know which base servers are gone:
                # its ring/buddy walks and live-member checks start from
                # the static spec otherwise
                srv_dead=sorted(self._dead_servers),
                srv_drained=sorted(self._drained_servers),
            )
            if hasattr(self.ep, "addr_map"):
                from adlb_tpu.runtime.membership import is_provisional

                resp["rank_addrs"] = {
                    r: a for r, a in self.ep.addr_map.items()
                    if r != rank and not is_provisional(r)
                }
        else:
            home = self._member_pick_home()
            fields = dict(mop="attach", rank=rank, home=home, epoch=epoch)
            # the joiner dialed only the master: it needs EVERY server's
            # listener (its home above all — FA_LOCAL_APP_DONE must land
            # there, or the home counts the rank unfinalized forever)
            srv_addrs = {}
            if hasattr(self.ep, "addr_map"):
                for r in self.world.server_ranks:
                    a = self.ep.addr_map.get(r) or self._member_addrs.get(r)
                    if a is not None:
                        srv_addrs[r] = a
            resp = dict(rc=ADLB_SUCCESS, rank=rank, home=home, epoch=epoch,
                        member=None, srv_addrs=srv_addrs,
                        srv_route=self._member_srv_route())
        if addr is not None:
            fields["host"], fields["port"] = addr
        self._member_barrier(fields, to=m.src, resp=resp)

    def _member_jobs_seed(self) -> list:
        from adlb_tpu.runtime.jobs import STATE_CODES

        return [
            (j.job_id, STATE_CODES[j.state], j.quota_bytes, j.name)
            for j in self.jobs.values() if j.job_id
        ]

    def _member_srv_route(self) -> dict:
        """Retired (dead/drained) server -> the LIVE ring successor that
        owns its shard today, chains collapsed. A joiner missed every
        TA_HOME_TAKEOVER broadcast that predates it, so the attach reply
        must seed its client-side route map directly — otherwise its
        round-robin puts dial the retired listener and time out waiting
        for a takeover note that will never re-arrive."""
        retired = self._dead_servers | self._drained_servers
        route = {}
        ring = self.world.server_ranks
        for r in retired:
            nxt = self.world.ring_next(r)
            for _ in range(len(ring)):
                if nxt not in retired and self._is_live_member(nxt):
                    break
                nxt = self.world.ring_next(nxt)
            if nxt not in retired and nxt != r:
                route[r] = nxt
        return route

    def _member_pick_home(self) -> int:
        """Least-loaded live server by homed-rank count — scale-out
        shards participate, which IS the TargetedDirectory rebalance:
        new ranks (and their targeted traffic) land on new capacity."""
        cands = [
            s for s in self.world.server_ranks
            if s not in self._dead_servers
            and s not in self._draining_servers
            and self._is_live_member(s)
        ]
        return min(cands, key=lambda s: (len(self.world.local_apps(s)), s))

    def _member_refuse(self, to: int, error: str, rc: int = -1) -> None:
        try:
            self.ep.send(
                to, msg(Tag.TA_MEMBER_RESP, self.rank, rc=rc, error=error),
                connect_grace=1.0,
            )
        except OSError:
            pass

    def _member_detach_req(self, m: Msg) -> None:
        rank = m.src
        if not self.is_master:
            self._member_refuse(rank, "detach goes to the master server")
            return
        if not self.world.is_app(rank):
            # idempotent: a re-sent detach after the first applied
            ok = rank in self.world.detached
            self._member_refuse(
                rank, "not a member", rc=ADLB_SUCCESS if ok else -1
            )
            return
        if self._member_terminating():
            # termination already counts the rank out as it finalizes;
            # refuse with the termination rc so the client falls back to
            # a plain finalize
            self._member_refuse(
                rank, "world terminating", rc=ADLB_NO_MORE_WORK
            )
            return
        epoch = self.world.epoch + 1
        self._member_barrier(
            dict(mop="detach", rank=rank, epoch=epoch),
            to=rank,
            resp=dict(rc=ADLB_SUCCESS, rank=rank, epoch=epoch),
        )

    def _member_barrier(self, fields: dict, to: int, resp: dict) -> None:
        """Apply a membership op locally, fan it to every live server,
        and hold the joiner's reply until all acks land (or the barrier
        deadline passes — the op is idempotent and applied everywhere
        responsive). The END ring defers while a barrier is open, so
        the epoch a token carries is never ahead of a voter."""
        self._member_tok += 1
        tok = self._member_tok
        need = set()
        for s in self._live_servers():
            if not self._is_live_member(s):
                continue
            try:
                self.ep.send(
                    s, msg(Tag.SS_MEMBER, self.rank, member_tok=tok,
                           **fields)
                )
                need.add(s)
            except OSError:
                self._note_server_unreachable(s)
        self._apply_member(dict(fields))
        p = {
            "need": need,
            "to": to,
            "resp": resp,
            "deadline": time.monotonic() + 5.0,
            "fields": fields,
        }
        if need:
            self._member_pending[tok] = p
        else:
            self._member_reply(p)

    def _member_reply(self, p: dict) -> None:
        resp = dict(p["resp"])
        if resp.get("member", "x") is None:
            # snapshot at REPLY time: attaches that completed while this
            # barrier was open are included
            resp["member"] = self.world.snapshot()
        try:
            self.ep.send(
                p["to"], msg(Tag.TA_MEMBER_RESP, self.rank, **resp),
                connect_grace=2.0,
            )
        except OSError:
            self.flight.record(
                f"member reply to {p['to']} undeliverable"
            )
        # a deferred END ring can proceed now
        self._maybe_complete_finalize()

    def _on_ss_member(self, m: Msg) -> None:
        mop = self._mstr(m.data.get("mop") or "")
        if mop == "ack":
            p = self._member_pending.get(m.data.get("member_tok"))
            if p is None:
                return
            p["need"].discard(m.src)
            if not p["need"]:
                del self._member_pending[m.data["member_tok"]]
                self._member_reply(p)
            return
        if mop == "ready":
            self._member_on_ready(m.src)
            return
        if mop == "rebalance":
            self._member_rebalance(int(m.data["dest"]))
            return
        if mop == "drain_done":
            rank = int(m.data["rank"])
            self._draining_servers.discard(rank)
            self._clean_retire.add(rank)
            # per-pair FIFO: every SS_REPL frame of the drain's final
            # flush was handled before this frame — the mirror here (if
            # we are the buddy) is COMPLETE, no EOF wait needed
            self._server_tail_drained.add(rank)
            self._on_server_dead(
                msg(Tag.SS_SERVER_DEAD, m.src, rank=rank,
                    epoch=int(m.data.get("epoch", 0) or 0), clean=1)
            )
            return
        if mop == "sync":
            self.world.seed(m.data.get("member") or {})
            for r, a in (m.data.get("addrs") or {}).items():
                if hasattr(self.ep, "addr_map"):
                    self.ep.addr_map.setdefault(int(r), tuple(a))
            for jid, code, quota, name in m.data.get("jobs") or ():
                # close the spawn-window gap: a job submitted / drained
                # / killed between this shard's FA_MEMBER seed and its
                # "ready" fan-out membership never reached it
                self.jobs.restore(jid, code, quota, name)
            self._g_epoch.set(self.world.epoch)
            return
        self._apply_member(dict(m.data))
        tok = m.data.get("member_tok")
        if tok:
            try:
                self.ep.send(
                    m.src, msg(Tag.SS_MEMBER, self.rank, mop="ack",
                               member_tok=tok)
                )
            except OSError:
                pass

    def _apply_member(self, d: dict) -> None:
        mop = self._mstr(d.get("mop") or "")
        epoch = int(d.get("epoch", 0) or 0)
        rank = int(d.get("rank", -1))
        host = d.get("host")
        if host is not None and hasattr(self.ep, "addr_map"):
            self.ep.addr_map.setdefault(
                rank, (self._mstr(host), int(d.get("port", 0)))
            )
        if mop == "attach":
            home = int(d["home"])
            self.world.add_app(rank, home, epoch)
            if home == self.rank:
                self.local_apps.add(rank)
                self._m_attached.inc()  # once fleet-wide: home counts
            self.flight.record(
                f"member_attach rank={rank} home={home} epoch={epoch}"
            )
        elif mop == "detach":
            self._apply_detach(rank, epoch)
        elif mop == "server_join":
            self.world.add_server(rank, epoch)
            self.peers.setdefault(rank, _PeerState())
            if self.is_master:
                self._m_servers_joined.inc()
            self.flight.record(
                f"member_server_join rank={rank} epoch={epoch}"
            )
        elif mop == "server_live":
            self._member_live.add(rank)
            self.world.note_epoch(epoch)
            # ring membership changed: if the live walk now puts the new
            # shard right after us, re-target the replication stream at
            # it (full-state bootstrap — its mirror starts empty)
            if self.cfg.on_server_failure == "failover":
                if not self._failover and self.world.nservers > 1:
                    self._failover = True
                nxt = self._ring_next_live()
                if (
                    self._failover
                    and nxt != self.rank
                    and (self.repl is None or self.repl.buddy != nxt)
                ):
                    self._rebootstrap_repl(nxt)
            self.flight.record(
                f"member_server_live rank={rank} epoch={epoch}"
            )
        elif mop == "server_drain":
            self._draining_servers.add(rank)
            self.world.note_epoch(epoch)
            self.flight.record(
                f"member_server_drain rank={rank} epoch={epoch}"
            )
            if rank == self.rank:
                self._begin_drain()
        # every membership change is activity: an in-flight exhaustion
        # vote must not conclude across it (the epoch stamp catches the
        # ring; this catches the master's own held vote)
        self.activity += 1
        self._exhaust_held_since = None
        self._g_epoch.set(self.world.epoch)
        # master: the deputy's brain mirror tracks every membership
        # mutation (epoch, watermark, homes, live/drained sets)
        self._repl_brain()

    def _apply_detach(self, rank: int, epoch: int) -> None:
        """A clean lease-draining rank-dead: the rank leaves membership
        and termination counting WITHOUT the death bookkeeping (no
        rank_dead count, no attempt bumps, no quarantine pressure).
        Journeys its departure touches carry a ``drain`` hop, so churn
        is visible in /trace/tails."""
        if rank in self.world.detached:
            return
        was_local = rank in self.local_apps
        self.world.remove_app(rank, epoch)
        if was_local:
            self._m_detached.inc()  # once fleet-wide: home counts
        # parked/steal state — same sweep as the death path
        self.rq.remove_rank(rank)
        self._stream_idle.discard(rank)
        self._swept_streams.discard(rank)
        self._rfr_out.pop(rank, None)
        self._rfr_excluded.pop(rank, None)
        self._park_res_local.pop(rank, None)
        self._seen_rqseqnos.pop(rank, None)
        self._last_heard.pop(rank, None)
        self._rank_job.pop(rank, None)
        # leases: drain cleanly — unpin and re-enqueue WITHOUT an
        # attempt bump (leaving is not a delivery failure)
        reclaimed = 0
        for lease in self.leases.owned_by(rank):
            self.leases.release(lease.seqno)
            unit = self.wq.get(lease.seqno)
            if unit is None or not unit.pinned or unit.pin_rank != rank:
                continue
            if self._relay_inflight.get(lease.seqno) == rank:
                # fused relay in flight: the payload may already be at
                # the leaver — at-most-once wins (delivered-at-detach)
                self._relay_inflight.pop(lease.seqno, None)
                self.journeys.forget(unit)
                self._consume(unit)
                continue
            if self._hedge_member_unpin(unit):
                # a hedge sibling still races: the leaver's copy retires
                reclaimed += 1
                continue
            self.wq.unpin(lease.seqno)
            if self.wlog is not None:
                self.wlog.log_unpin(lease.seqno)
            if unit.spans is not None:
                self.journeys.stamp(unit, "drain")
            if unit.common_seqno >= 0:
                self._forfeit_common(
                    unit.common_seqno, unit.common_server_rank,
                    op="credit",
                )
            reclaimed += 1
        if reclaimed:
            self._m_leases_reclaimed.inc(reclaimed)
        # targeted units for the leaver can never be fetched: drop them
        # (refcount-correct), closing their journeys through the drain
        doomed = [u for u in self.wq.units() if u.target_rank == rank]
        for u in doomed:
            self.wq.remove(u.seqno)
            self.leases.release(u.seqno)
            self._spill_drop(u)
            self.mem.free(len(u.payload))
            if u.spans is not None:
                self.journeys.stamp(u, "drain")
                self.journeys.close(u, "dropped")
            if self.wlog is not None:
                self.wlog.log_remove(u.seqno)
            self._forfeit_common(u.common_seqno, u.common_server_rank)
        self.tq.drop_rank(rank)
        if was_local:
            self.local_apps.discard(rank)
            self._finalized.discard(rank)
        if self.is_master and self.cfg.balancer == "tpu":
            self._patch_snapshots_for_dead(rank)
        if reclaimed:
            self._match_rq()
        self.flight.record(
            f"member_detach rank={rank} epoch={epoch} "
            f"reclaimed={reclaimed} targeted_dropped={len(doomed)}"
        )
        # the leaver no longer gates END: its home may be complete now
        self._maybe_complete_finalize()

    def _member_on_ready(self, new: int) -> None:
        """Master: a scale-out shard's reactor is up. Publish it live
        (everyone adds it to rings/buddy walks), sync it to the freshest
        membership, and direct a donor rebalance at it."""
        if not self.is_master or new in self._member_ready:
            return
        self._member_ready.add(new)
        self._member_live.add(new)
        epoch = self.world.epoch + 1
        self.world.note_epoch(epoch)
        # fresh membership + learned addresses for the late arrival —
        # and the job table AGAIN: it was seeded at FA_MEMBER time, and
        # any /jobs submit/drain/kill during the spawn window fanned out
        # to _live_servers(), which excluded the not-yet-ready shard
        try:
            self.ep.send(
                new, msg(Tag.SS_MEMBER, self.rank, mop="sync",
                         member=self.world.snapshot(),
                         addrs=dict(self._member_addrs),
                         jobs=self._member_jobs_seed()),
            )
        except OSError:
            self._note_server_unreachable(new)
            return
        for s in self._live_servers():
            try:
                self.ep.send(
                    s, msg(Tag.SS_MEMBER, self.rank, mop="server_live",
                           rank=new, epoch=epoch),
                )
            except OSError:
                pass
        self._apply_member(dict(mop="server_live", rank=new, epoch=epoch))
        # donor: the most loaded live shard sheds backlog to the new one
        cands = [
            s for s in self.world.server_ranks
            if s != new and s not in self._dead_servers
            and s not in self._draining_servers and self._is_live_member(s)
        ]
        def load(s):
            if s == self.rank:
                return self.mem.curr
            p = self.peers.get(s)
            return p.nbytes if p is not None else 0
        donor = max(cands, key=load) if cands else self.rank
        if self._scaleout_t0 is not None:
            mttr = (time.monotonic() - self._scaleout_t0) * 1e3
            self._g_scaleout_mttr.set(mttr)
            self._scaleout_t0 = None
            self.flight.record(
                f"scaleout_ready rank={new} donor={donor} "
                f"mttr_ms={mttr:.1f}"
            )
        if donor == self.rank:
            self._member_rebalance(new)
        else:
            try:
                self.ep.send(
                    donor, msg(Tag.SS_MEMBER, self.rank, mop="rebalance",
                               dest=new),
                )
            except OSError:
                self._note_server_unreachable(donor)

    def _member_rebalance(self, dest: int) -> None:
        """Donor side of scale-out bootstrap: ship a fair share of the
        unpinned untargeted backlog to the new shard over the ACKED
        migration plane (serialized-unit wire format; a dest death
        mid-transit hands the units back via _migrate_pending), so
        every put acked before the scale-out stays fetchable after it.
        Shipped journeys gain an ``attach`` hop — scale-out churn is
        visible in /trace/tails."""
        if dest in self._dead_servers or self.done:
            return
        pool = [
            u for u in self.wq.units()
            if not u.pinned and u.target_rank < 0 and u.job == 0
        ]
        n_live = max(
            len([
                s for s in self.world.server_ranks
                if s not in self._dead_servers and self._is_live_member(s)
            ]),
            2,
        )
        take = len(pool) // n_live
        if take <= 0:
            return
        pool.sort(key=lambda u: u.time_stamp)  # coldest first
        units = []
        for unit in pool[:take]:
            self._unspill(unit)
            self.wq.remove(unit.seqno)
            self.mem.free(len(unit.payload))
            if self.wlog is not None:
                self.wlog.log_remove(unit.seqno)
            if unit.spans is not None:
                self.journeys.stamp(unit, "attach")
            shipped = {
                "payload": unit.payload,
                "work_type": unit.work_type,
                "prio": unit.prio,
                "answer_rank": unit.answer_rank,
                "home_server": unit.home_server,
                "common_len": unit.common_len,
                "common_server": unit.common_server_rank,
                "common_seqno": unit.common_seqno,
                "time_stamp": unit.time_stamp,
                "attempts": unit.attempts,
            }
            if getattr(unit, "job", 0):
                # namespace rides the move (omitted = job 0, so
                # single-job batches stay byte-identical on the wire)
                shipped["job"] = unit.job
            tf = trace_fields(unit)
            if tf is not None:
                shipped["trace"] = tf
                self.journeys.forget(unit)
            units.append(shipped)
        self.activity += 1
        self._exhaust_held_since = None
        self.flight.record(
            f"scaleout_rebalance dest={dest} shipped={len(units)} "
            f"of={len(pool)}"
        )
        self._send_migrate_batch(dest, units, bounced=False)

    def _begin_drain(self) -> None:
        """This server is being scaled IN. Two phases: mark draining —
        from here no NEW custody is accepted (push queries refuse,
        peers' target pickers skip us) — then, once the custody already
        accepted settles (in-flight SS_PUSH_WORK payloads land),
        :meth:`_maybe_finish_drain` flushes a FULL-state replication
        bootstrap to the buddy, announces drain_done behind the stream
        tail, and exits. The buddy promotes a complete mirror — zero
        counted losses by construction."""
        if self._draining_self or self.done:
            return
        from adlb_tpu.runtime import replica

        buddy = replica.buddy_of(
            self.world, self.rank, self._buddy_excluded()
        )
        if buddy == self.rank:
            self.flight.record("drain refused: no live buddy")
            return
        self._draining_self = True
        # bounded: a pusher that died between QUERY_RESP and WORK would
        # otherwise park this drain on a reservation that never lands
        self._drain_deadline = time.monotonic() + 5.0
        self._maybe_finish_drain()

    def _maybe_finish_drain(self) -> None:
        if not self._draining_self or self.done:
            return
        if self._push_reserved and time.monotonic() < self._drain_deadline:
            return  # accepted pushes still in flight toward us
        from adlb_tpu.runtime import replica

        buddy = replica.buddy_of(
            self.world, self.rank, self._buddy_excluded()
        )
        if self.spill is not None:
            self._spill_fault_in_all()
        for u in self.wq.units():
            if u.spans is not None:
                self.journeys.stamp(u, "drain")
        self._failover = True  # the promote plane is the drain plane
        self._rebootstrap_repl(buddy)
        self._flush_repl()
        note_epoch = self.world.epoch + 1
        for s in self._live_servers():
            try:
                self.ep.send(
                    s, msg(Tag.SS_MEMBER, self.rank, mop="drain_done",
                           rank=self.rank, epoch=note_epoch),
                )
            except OSError:
                pass
        self.flight.record(f"drained to buddy {buddy}; exiting")
        self._drained_exit = True
        self.done = True

    def _maybe_autoscale(self, now: float) -> None:
        """Master, Config(elastic_scaleout='auto'): when any live server
        crosses the soft memory watermark, add a shard BEFORE the spill
        tier or backpressure engage."""
        if (
            self._scaleout_t0 is not None
            or self._scale_pending is not None
            or self._member_terminating()
            or now < self._elastic_cooldown_until
        ):
            return
        soft = self.cfg.max_malloc_per_server * self.cfg.mem_soft_frac
        hot = self.rank if self.mem.curr >= soft else None
        if hot is None:
            for s, p in self.peers.items():
                if (
                    s != self.rank
                    and s not in self._dead_servers
                    and p.nbytes >= soft
                ):
                    hot = s
                    break
        if hot is None:
            return
        self._elastic_cooldown_until = now + self.cfg.elastic_cooldown_s
        self._request_scale_out("mem_watermark", hot_rank=hot)

    @property
    def member_spawner(self):
        """Harness hook: callable(alloc) that spawns a new server shard
        (in-proc thread, subprocess, k8s pod — the harness's business)."""
        return self._member_spawner

    @member_spawner.setter
    def member_spawner(self, fn) -> None:
        self._member_spawner = fn
        if fn is None:
            return
        # Drain the parked scale request on registration (PR 19): a
        # watermark/controller scale-out that arrived spawnerless parks
        # in the single _scale_pending slot (dedup-collapsed — each new
        # request overwrites, newest wins). A late-registering spawner
        # must service it now, not leave it to rot at /fleet until the
        # next trigger re-fires.
        pending = getattr(self, "_scale_pending", None)
        if pending is None:
            return
        if self._scaleout_t0 is not None or self._member_terminating():
            return
        self._scale_pending = None
        if self.is_master and self._failover and self.repl is not None:
            self.repl.log_scale(None)  # the clearing replicates too
        self.flight.record(
            f"scale_pending_drained reason={pending.get('reason')}"
        )
        self._request_scale_out(
            str(pending.get("reason") or "pending"),
            hot_rank=pending.get("hot_rank"),
        )

    def _request_scale_out(self, reason: str,
                           hot_rank: Optional[int] = None) -> dict:
        self.flight.record(
            f"scale_out_requested reason={reason} hot={hot_rank}"
        )
        if self.member_spawner is None:
            # no spawner registered: park the request, visible at /fleet
            # (the future autoscaler's feed)
            self._scale_pending = {
                "reason": reason, "hot_rank": hot_rank,
                "at": time.time(),
            }
            if self.is_master and self._failover and self.repl is not None:
                # a parked request is brain state: the deputy's /fleet
                # must show it (and its spawner must drain it) after a
                # takeover
                self.repl.log_scale(dict(self._scale_pending))
            return {"requested": False, "pending": True}
        self._scaleout_t0 = time.monotonic()
        try:
            self.member_spawner({"kind": "server", "reason": reason})
        except Exception as e:  # noqa: BLE001 — a broken spawner must
            # not crash the reactor
            self._scaleout_t0 = None
            self._scale_pending = {
                "reason": reason, "error": repr(e), "at": time.time(),
            }
            if self.is_master and self._failover and self.repl is not None:
                self.repl.log_scale(dict(self._scale_pending))
            return {"requested": False, "pending": True,
                    "error": repr(e)}
        return {"requested": True}

    def fleet_doc(self) -> dict:
        """GET /fleet: the live topology + per-rank epoch/state view
        (read by the ops HTTP thread — copies, no mutation). Membership
        containers are snapshotted with the registry's retry discipline
        first: the reactor inserts into extra_apps/detached during an
        attach, and iterating them live would raise RuntimeError exactly
        when /fleet matters most — mid-churn."""
        w = self.world

        def stable(container, ctor):
            for _ in range(8):
                try:
                    return ctor(container)
                except RuntimeError:
                    continue
            return ctor(())

        extra_apps = stable(w.extra_apps, dict)
        detached = stable(w.detached, set)
        servers = []
        for s in list(w.server_ranks):
            if s in self._drained_servers:
                state = "drained"
            elif s in self._dead_servers:
                state = "dead"
            elif s in self._draining_servers:
                state = "draining"
            elif self._is_live_member(s):
                state = "live"
            else:
                state = "joining"
            servers.append({
                "rank": s,
                "state": state,
                "master": s == w.master_server_rank,
                "extra": s not in w.spec.server_ranks,
            })
        apps = []
        ranks = [r for r in w.spec.app_ranks if r not in detached]
        ranks += [r for r in extra_apps if r not in detached]
        for r in ranks:
            if r in extra_apps:
                home = extra_apps[r]
            else:
                home = w.home_server(r)
            if r in self._dead_ranks:
                state = "dead"
            elif r in self._finalized:
                state = "finalized"
            else:
                state = "live"
            apps.append({
                "rank": r,
                "home": home,
                "state": state,
                "attached": r >= w.spec.num_app_ranks,
            })
        return {
            "epoch": w.epoch,
            "master": w.master_server_rank,
            "nservers_live": sum(
                1 for s in servers if s["state"] == "live"
            ),
            "servers": servers,
            "apps": apps,
            "detached": sorted(detached),
            "scale_pending": self._scale_pending,
        }

    # ------------------------------------------------- worker-death reclaim
    # No reference analogue (upstream: any rank failure kills the job,
    # src/adlb.c:2508-2526). Under Config(on_worker_failure="reclaim") an
    # app rank's death is absorbed: its home server fans out SS_RANK_DEAD
    # and every server (a) re-enqueues the rank's leased-but-unfetched
    # units, (b) drops its rq/steal state and targeted work (with a
    # refcount-correct batch-common release), (c) excludes it from
    # termination counting, and (d) — master — patches the balancer's
    # requester snapshots so the dead rank stops attracting matches and
    # migrations. Server death still aborts under both policies.

    def _declare_rank_dead(self, rank: int) -> None:
        """Home server: fan out the death and reclaim locally."""
        if rank in self._dead_ranks:
            return
        for srv in self._live_servers():
            try:
                self.ep.send(srv, msg(Tag.SS_RANK_DEAD, self.rank, rank=rank))
            except OSError:
                pass  # peer already ended: no state left to clean there
        self._on_rank_dead(msg(Tag.SS_RANK_DEAD, self.rank, rank=rank))

    def _on_rank_dead(self, m: Msg) -> None:
        rank = m.rank
        if rank in self._dead_ranks:
            return
        self._dead_ranks.add(rank)
        self._m_rank_dead.inc()
        if self.wlog is not None:
            self.wlog.log_rank_dead(rank)
        self.flight.record(f"rank_dead rank={rank} declared_by={m.src}")
        # 1) the dead requester's park/steal state (every entry — a
        # streaming rank may hold several prefetch slots). Flag the rank
        # unconditionally: if it was streaming, ANY of its in-flight
        # slots may now be phantom — including ones whose entries were
        # already matched but whose responses died with the connection
        # (remove_rank returns [] then) — and a resurrected stream's
        # next idle note re-arms them (see _on_stream_idle). For a
        # non-streaming rank the flag is inert (it never sends idle).
        self.rq.remove_rank(rank)
        self._swept_streams.add(rank)
        # reset the request-id window: the swept-stream re-arm reads
        # "claimed id not in the window" as "request or response died
        # with the connection" — ids must only accumulate again from
        # post-death (post-resurrection) traffic
        self._seen_rqseqnos.pop(rank, None)
        self._stream_idle.discard(rank)
        self._rfr_out.pop(rank, None)
        self._rfr_excluded.pop(rank, None)
        self._park_res_local.pop(rank, None)
        # 2) reclaim leases: pinned-but-unfetched units return to the queue
        reclaimed = 0
        for lease in self.leases.owned_by(rank):
            self.leases.release(lease.seqno)
            unit = self.wq.get(lease.seqno)
            if unit is not None and unit.pinned and unit.pin_rank == rank:
                if self._relay_inflight.get(lease.seqno) == rank:
                    # remote fused fetch in flight to the dead rank: the
                    # payload may already have LANDED there (the home
                    # forwards before confirming), so re-enqueueing could
                    # run the unit twice if the EOF was churn and the
                    # rank resurrects. At-most-once delivery wins: treat
                    # it as delivered-at-death and drop it — the same
                    # outcome as a unit fetched via GET_RESERVED just
                    # before the owner died. NO common forfeit here: the
                    # dead client may already have accounted its prefix
                    # share (it fetches at decode time, before death was
                    # observed), and an over-forfeit would GC the prefix
                    # under a live member — the bounded-leak direction
                    # (prefix outlives the batch if the client never
                    # accounted) is the acceptable one, as everywhere
                    # else in the common accounting.
                    self._relay_inflight.pop(lease.seqno, None)
                    # the home server (if the payload landed) closed the
                    # relayed journey; our copy just releases
                    self.journeys.forget(unit)
                    self._consume(unit)
                    self.flight.record(
                        f"relay_consumed_on_death seqno={lease.seqno} "
                        f"rank={rank}"
                    )
                    continue
                if self._hedge_member_unpin(unit):
                    # a hedge sibling still races for this logical put:
                    # the dead owner's copy retires instead of becoming
                    # a second live duplicate in open matching
                    reclaimed += 1
                    continue
                self.wq.unpin(lease.seqno)
                if self.wlog is not None:
                    self.wlog.log_unpin(lease.seqno)
                # retry budget: a unit that serially kills its owners
                # (poison) must not re-enqueue forever
                quarantined = self._bump_attempts(unit, in_wq=True)
                if unit.common_seqno >= 0 and not quarantined:
                    # the dead owner may have fetched the batch-common
                    # prefix already; the re-consumption will fetch it
                    # again, so grant the prefix one extra expected get.
                    # On quarantine, NO op (as in _expire_lease): a
                    # credit expects a re-consumption that never comes,
                    # a forfeit could over-count a fetch the dead owner
                    # already accounted and GC the prefix under a live
                    # member
                    self._forfeit_common(
                        unit.common_seqno, unit.common_server_rank,
                        op="credit",
                    )
                reclaimed += 1
                self.flight.record(
                    f"lease_reclaimed seqno={lease.seqno} "
                    f"lease_id={lease.lease_id} rank={rank}"
                )
        if reclaimed:
            self._m_leases_reclaimed.inc(reclaimed)
            # reclaim is activity: an in-flight exhaustion vote must not
            # conclude around work that just became available again
            self.activity += 1
            self._exhaust_held_since = None
        # 3) drop units targeted at the dead rank (nobody else may take
        # them), releasing their batch-common refcounts
        doomed = [u for u in self.wq.units() if u.target_rank == rank]
        for u in doomed:
            self.wq.remove(u.seqno)
            self.leases.release(u.seqno)
            self._spill_drop(u)
            self.mem.free(len(u.payload))
            if u.spans is not None:
                self.journeys.close(u, "dropped")
            if self.wlog is not None:
                self.wlog.log_remove(u.seqno)
            self._m_targeted_dropped.inc()
            self._forfeit_common(u.common_seqno, u.common_server_rank)
            self.flight.record(
                f"targeted_dropped rank={rank} seqno={u.seqno}"
            )
        self.tq.drop_rank(rank)
        # 4) termination counting: the rank will never send LOCAL_APP_DONE
        if rank in self.local_apps:
            self._finalized.add(rank)
            self._maybe_complete_finalize()
        # 5) balancer view (master, tpu mode): retire the dead requester
        # from every held snapshot so plans stop targeting it
        if self.is_master and self.cfg.balancer == "tpu":
            self._patch_snapshots_for_dead(rank)
        # reclaimed inventory may satisfy surviving parked requesters
        if reclaimed:
            self._match_rq()
        # a survived death still leaves a post-mortem artifact (when a
        # flight dir is configured): the world lives on, but the operator
        # needs the who-died/what-was-reclaimed timeline
        # (scripts/obs_report.py merges these across ranks)
        self.flight.dump_json(f"rank_dead_{rank}")

    def _patch_snapshots_for_dead(self, rank: int) -> None:
        for src, snap in self._snapshots.items():
            reqs = snap.get("reqs") or []
            kept = [r for r in reqs if r[0] != rank]
            if len(kept) != len(reqs):
                snap["reqs"] = kept
                # no stamp bump (it would re-eligibilize the ledger);
                # the sequence carries the in-place patch to the
                # sharded solver's unchanged-server fast path
                snap["req_seq"] = snap.get("req_seq", 0) + 1
                self._snapshots.bump(src)  # in-place patch: version it
                self._req_sigs[src] = tuple(
                    sorted((r[0], r[1]) for r in kept)
                )
                self._broadcast_hungry(
                    self._hungry_tracker.update(src, kept)
                )
        if self._balancer is not None:
            self._balancer.wake.set()

    def _forfeit_common(self, common_seqno, common_server,
                        op: str = "forfeit") -> None:
        """Fix up a batch-common refcount for a reclaimed member unit:
        ``forfeit`` accounts a get that will never happen (unit dropped),
        ``credit`` expects one extra get (unit re-enqueued; its dead
        owner may already have fetched the prefix). Local when this
        server stores the prefix, else via SS_COMMON_FORFEIT."""
        if common_seqno is None or common_seqno < 0:
            return
        if common_server is None or common_server == self.rank:
            self._apply_common_op(common_seqno, op)
        else:
            self._send_srv(
                common_server,
                msg(Tag.SS_COMMON_FORFEIT, self.rank,
                    common_seqno=common_seqno, op=op),
            )

    def _apply_common_op(self, common_seqno: int, op: str,
                         src: int = -1, op_id: int = -1) -> None:
        if self.wlog is not None:
            self.wlog.log_common_op(
                common_seqno, "credit" if op == "credit" else "forfeit",
                src, op_id,
            )
        if op == "credit":
            self.cq.credit(common_seqno)
        else:
            self.cq.forfeit(common_seqno)

    def _on_common_forfeit(self, m: Msg) -> None:
        fo = m.data.get("fo_from")
        if fo is not None:
            new = self._adopted_common_for(fo, m.common_seqno)
            if new is None:
                return  # prefix did not survive the takeover
            m.data["common_seqno"] = new
        fid = m.data.get("get_id")
        if fid is not None:
            # client cache-hit accounting notes carry an id: a note
            # re-sent across connection churn must not be applied twice
            # (an over-forfeit would GC the prefix one get early, under
            # a live member). A windowed seen-set like the reserve
            # dedup — a re-send on a new connection can be processed
            # before an older note still queued from the old one, so a
            # last-id equality check is not enough. Server-to-server
            # fixups carry no id.
            if self._window_seen(self._seen_forfeits, m.src, fid):
                return
        op = m.data.get("op", "forfeit")
        if isinstance(op, bytes):  # binary-codec peers carry it as bytes
            op = op.decode()
        self._apply_common_op(m.common_seqno, op, m.src,
                              fid if fid is not None else -1)

    def _resurrect(self, rank: int) -> None:
        """A rank we declared dead is talking again: the EOF was network
        churn. Its reclaimed state stays reclaimed (at-most-once for its
        old leases/targeted units), but the rank itself rejoins the
        world's accounting and is served again."""
        self._dead_ranks.discard(rank)
        self._resurrected.add(rank)
        self._m_reconnects.inc()
        self.flight.record(f"reconnect rank={rank} (was declared dead)")
        if rank in self.local_apps:
            self._finalized.discard(rank)

    # ------------------------------------------------- server failover
    # Config(on_server_failure="failover"); no reference analogue — the
    # reference's servers ARE the pool and any server death kills the job
    # (SURVEY §5). Every server streams a replication log of its pool
    # mutations to its ring-successor buddy (adlb_tpu/runtime/replica.py,
    # SS_REPL frames reusing the checkpoint.py unit wire format) and
    # passively mirrors its ring predecessor. On a server's EOF the first
    # observer fans out SS_SERVER_DEAD; every survivor prunes the dead
    # server from rings/gossip/plans and reroutes through its buddy; the
    # buddy replays the mirror into its own queues — pinned units stay
    # pinned under their leases behind a seqno translation, unpinned
    # units re-enqueue — adopts the dead server's app ranks, and remaps
    # clients via epoch-stamped TA_HOME_TAKEOVER.

    def _live_servers(self) -> list:
        return [
            s for s in self.world.server_ranks
            if s != self.rank and s not in self._dead_servers
            and self._is_live_member(s)
        ]

    def _ring_next_live(self) -> int:
        nxt = self.world.ring_next(self.rank)
        while nxt != self.rank and (
            nxt in self._dead_servers or not self._is_live_member(nxt)
        ):
            nxt = self.world.ring_next(nxt)
        return nxt

    def _ring_forward(self, make_msg) -> None:
        """Forward a ring token to the next live successor; a peer that
        turns out unreachable is noted (death evidence under failover)
        and the recomputed successor tried instead. When this server is
        the only live one the token self-delivers — exactly the
        single-server ring shape the termination protocols already
        handle."""
        for _ in range(self.world.nservers):
            nxt = self._ring_next_live()
            try:
                self.ep.send(nxt, make_msg(nxt))
                return
            except OSError:
                if not self._failover or nxt == self.rank:
                    raise
                self._note_server_unreachable(nxt)

    def _send_srv(self, dest: int, m: Msg):
        """Server->server send that survives failover: a dead destination
        reroutes to its buddy — stamped ``fo_from`` so content-addressed
        seqnos translate through the takeover maps — and an unreachable
        one becomes death evidence instead of a reactor crash. Returns
        the rank actually sent to, or None when the send was absorbed."""
        routed = dest
        seen = set()
        while routed in self._dead_servers:
            nxt = self._srv_route.get(routed)
            if nxt is None or nxt in seen:
                return None
            seen.add(nxt)
            routed = nxt
        if routed != dest:
            m.data.setdefault("fo_from", dest)
        try:
            self.ep.send(routed, m)
            return routed
        except OSError:
            if not self._failover:
                raise
            self.flight.record(
                f"send to server {routed} failed ({m.tag.name})"
            )
            self._note_server_unreachable(routed)
            return None

    def _note_server_unreachable(self, srv: int) -> None:
        """A send to a supposedly-live server failed: treat it as death
        evidence (the EOF may simply not have reached us yet)."""
        if self.world.is_server(srv) and not self._is_live_member(srv):
            # a joined-but-never-live scale-out shard: its absence must
            # not abort the world it never served
            self.flight.record(f"joining server {srv} unreachable")
            return
        plan = getattr(self.ep, "plan", None)
        if plan is not None and getattr(plan, "disconnected", False):
            # OUR endpoint is the dead one (fault-injected server death):
            # every send fails, and blaming the peers would abort the
            # world this policy exists to save — die quietly instead
            # (_run_loop classifies the casualty)
            raise OSError(
                f"server {self.rank}: own connectivity lost"
            )
        if (
            srv in self._dead_servers
            or not self.world.is_server(srv)
            or srv == self.rank
            or self.done
        ):
            return
        self._server_eof_at.setdefault(srv, time.monotonic())
        if self._failover and self._can_failover(srv):
            self._declare_server_dead(srv)
        else:
            self._do_abort(-3, broadcast=True)

    # -- replication (primary side) -----------------------------------------

    def _on_common_gc(self, e) -> None:
        self.mem.free(len(e.buf))
        if self.wlog is not None:
            self.wlog.log_common_op(e.seqno, "gc")

    def _flush_repl(self) -> None:
        r = self.repl
        if r is None:
            return
        self._g_repl_lag.set(r.pending)
        blob = r.take()
        if blob is None:
            return
        try:
            self.ep.send(
                r.buddy, msg(Tag.SS_REPL, self.rank, blob=blob, seq=r.seq)
            )
        except OSError:
            self.flight.record("replication flush failed (buddy gone?)")
            self._note_server_unreachable(r.buddy)

    def _brain_doc(self) -> dict:
        """The master-only durable control-plane state, as one pickled
        snapshot for the deputy's mirror (OP_MEMBER, newest wins). Soft
        state — merged obs registry, p99 thresholds, alert lifecycle,
        profiler stacks — is deliberately NOT here: gossip snapshots are
        cumulative, so the fleet view reconstructs at the new master
        within one sync interval."""
        return {
            "master": self.rank,
            "epoch": self.world.epoch,
            "next_rank": self._member_next_rank,
            "member": self.world.snapshot(),
            "addrs": dict(self._member_addrs),
            "live": sorted(self._member_live),
            "ready": sorted(self._member_ready),
            "dead": sorted(self._dead_servers),
            "drained": sorted(self._drained_servers),
            "srv_route": self._member_srv_route(),
            "job_next_id": self._job_next_id,
            # whether this world is observed: the deputy has ops_port
            # stripped from its own cfg (scale-out shards) or may share
            # the port in-proc — promotion rebinds ephemeral when armed
            "ops_armed": self.cfg.ops_port is not None or (
                self.ops is not None
            ),
        }

    def _repl_brain(self) -> None:
        """Master: stream the brain snapshot to the deputy. Called on
        every membership/route mutation; a non-master (or unconfigured)
        world never emits these, keeping frame identity."""
        if self.is_master and self._failover and self.repl is not None:
            self.repl.log_member(self._brain_doc())

    def _rebootstrap_repl(self, new_buddy: int) -> None:
        """Our buddy died: re-target the replication stream at the next
        live successor, seeding it with a full-state bootstrap (the
        mirror there starts empty)."""
        from adlb_tpu.runtime import replica

        if new_buddy == self.rank:
            self.repl = None  # no live peer left to replicate to
            self._refresh_wlog()
            return
        r = replica.ReplicationLog(new_buddy)
        for u in self.wq.units():
            r.log_put(u, -1, None)  # carries the pin state
        for e in self.cq.entries():
            r.log_common_put(e.seqno, e.buf)
            r.log_common_state(e.seqno, e.refcnt, e.ngets, e.credits)
        for rank in self._finalized:
            r.log_app_done(rank)
        for rank in self._dead_ranks:
            r.log_rank_dead(rank)
        # gray-failure state: fences and the dead-letter store must
        # survive this server's own later death, or a takeover would
        # un-fence stalled owners and silently drop the quarantine count
        for seqno, owner in self._fences:
            r.log_fence(seqno, owner)
        for origin, seqno, owner in self._adopted_fences:
            # fences adopted from predecessors keep their origin — a
            # doubly-rerouted late fetch stamps the ORIGINAL home
            r.log_fence(seqno, owner, origin=origin)
        for q in self.quarantine:
            r.log_put(
                WorkUnit(
                    seqno=q["seqno"],
                    work_type=q["work_type"],
                    prio=q["prio"],
                    target_rank=q["target_rank"],
                    answer_rank=q["answer_rank"],
                    payload=q["payload"],
                    attempts=q["attempts"],
                    common_len=q.get("common_len", 0),
                    common_server_rank=q.get("common_server_rank", -1),
                    common_seqno=q.get("common_seqno", -1),
                ),
                -1, None,
            )
            r.log_quarantine(q["seqno"])
        # dedup windows: without these, a put this server acked (or a
        # get/forfeit it accounted) re-sent after a later death of THIS
        # server would be applied twice by the new buddy
        for src, (_ids, order) in self._seen_puts.items():
            r.log_seen_puts(src, order)
        for src, gid in self._last_common.items():
            r.log_common_op(-1, "get", src, gid)
        for src, (_ids, order) in self._seen_forfeits.items():
            for fid in order:
                r.log_common_op(-1, "forfeit", src, fid)
        if self.is_master:
            # the new buddy is the new DEPUTY: bootstrap the whole brain
            # (the per-event streaming below only ships changes)
            r.log_member(self._brain_doc())
            if self._slo_engine is not None:
                for o in self._slo_engine.objectives:
                    r.log_slo(dict(o))
            if self._controller is not None:
                r.log_control(self._controller.policy_doc())
            if self._scale_pending is not None:
                r.log_scale(dict(self._scale_pending))
            for j in self.jobs.values():
                if j.weight != 1.0:
                    r.log_job_weight(j.job_id, j.weight)
        self.repl = r
        self._refresh_wlog()
        self.flight.record(
            f"replication re-bootstrapped to server {new_buddy} "
            f"({len(list(self.wq.units()))} units)"
        )

    def _on_repl(self, m: Msg) -> None:
        if not self._failover and m.src not in self._draining_servers:
            return  # a misconfigured peer's stream is ignorable
        from adlb_tpu.runtime import replica

        self.mirrors.setdefault(
            m.src, replica.ReplicaMirror(m.src)
        ).apply(m.blob)

    # -- death detection & fan-out ------------------------------------------

    def _can_failover(self, dead: int) -> bool:
        """A server with a live buddy candidate can fail over — the
        MASTER included: its ring buddy is the standing deputy, holding
        the replicated brain (see _promote_master). Only the no-live-
        peer case (last pair dying together) still aborts."""
        if not self._failover:
            return False
        from adlb_tpu.runtime import replica

        return replica.buddy_of(
            self.world, dead, self._buddy_excluded()
        ) != dead

    def _on_server_eof(self, src: int) -> None:
        """A server peer's connection closed mid-run (before this server
        is done): death, unless termination is underway — a finished peer
        exits normally then, so during termination the death is only
        *suspected* and declared if the world has not completed shortly."""
        self._server_eof_at.setdefault(src, time.monotonic())
        # genuine inbound EOF: handled in queue order, so every SS_REPL
        # frame this connection carried has already been applied
        self._server_tail_drained.add(src)
        if src in self._pending_promotion:
            # the fan-out beat the EOF here; the EOF closes the tail
            # window — every replication frame from src has now drained
            del self._pending_promotion[src]
            self._promote(src)
            return
        if src in self._dead_servers:
            return
        if self.no_more_work or self.done_by_exhaustion or self._ending:
            if self._failover and self._can_failover(src):
                self._suspect_servers.setdefault(
                    src, time.monotonic() + 2.0
                )
            return  # abort policy: benign, as in the reference teardown
        if self._failover and self._can_failover(src):
            aprintf(
                True, self.rank,
                f"server rank {src} connection lost mid-run; failing over "
                f"(on_server_failure=failover)",
            )
            self._declare_server_dead(src)
            return
        aprintf(
            True, self.rank,
            f"server rank {src} connection lost mid-run; aborting",
        )
        self._do_abort(-3, broadcast=True)

    def _declare_server_dead(self, dead: int) -> None:
        if dead in self._dead_servers or self.done:
            return
        epoch = self.world.epoch + 1
        for s in self._live_servers():
            if s == dead:
                continue
            try:
                self.ep.send(
                    s, msg(Tag.SS_SERVER_DEAD, self.rank, rank=dead,
                           epoch=epoch)
                )
            except OSError:
                pass  # its own EOF/evidence will catch up
        self._on_server_dead(
            msg(Tag.SS_SERVER_DEAD, self.rank, rank=dead, epoch=epoch)
        )

    def _on_server_dead(self, m: Msg) -> None:
        dead = m.rank
        if dead in self._dead_servers or dead == self.rank:
            return
        from adlb_tpu.runtime import replica

        # clean retire (elastic scale-in drain_done): the shard was
        # fully shipped to the buddy BEFORE this frame, so the promote
        # counts no losses and the death-vs-drain metrics split
        clean = bool(m.data.get("clean")) or dead in self._clean_retire
        if not clean and not self._can_failover(dead):
            # no live buddy left (the last pair died together, or the
            # policy is off): unrecoverable
            aprintf(
                True, self.rank,
                f"server rank {dead} died and cannot fail over "
                f"(no live buddy); aborting",
            )
            self._do_abort(-3, broadcast=True)
            return
        self._dead_servers.add(dead)
        self._suspect_servers.pop(dead, None)
        self._draining_servers.discard(dead)
        if clean:
            self._clean_retire.add(dead)
            self._drained_servers.add(dead)
        self.world.note_epoch(m.data.get("epoch", 0) or 0)
        self._g_epoch.set(self.world.epoch)
        buddy = replica.buddy_of(self.world, dead, self._buddy_excluded())
        self._srv_route[dead] = buddy
        if clean:
            self._m_servers_drained.inc()
        else:
            self._m_server_dead.inc()
        # master: the retired-route map just changed — the deputy's
        # brain must carry it (a promoted master seeds joiners from it)
        self._repl_brain()
        # a retired server can never ack a membership fan-out: release
        # any barrier waiting on it
        for tok in [
            t for t, p in self._member_pending.items()
            if dead in p["need"]
        ]:
            p = self._member_pending[tok]
            p["need"].discard(dead)
            if not p["need"]:
                del self._member_pending[tok]
                self._member_reply(p)
        # ... and a dead server can never ack the succession barrier
        if self._takeover_pending is not None:
            self._takeover_pending["need"].discard(dead)
            if not self._takeover_pending["need"]:
                self._master_takeover_done()
        # master: the retired shard's obs-gossip snapshots must not
        # report stale forever on /healthz (/fleet keeps the topology
        # history; the staleness ledger is for LIVE members)
        if self.is_master:
            self._fleet_seen.pop(dead, None)
            self._fleet_snaps.pop(dead, None)
            self._prof_fleet.pop(dead, None)
            self._prof_windows.pop(dead, None)
            self._member_ready.discard(dead)
        self.flight.record(
            f"server_{'drained' if clean else 'dead'} rank={dead} "
            f"declared_by={m.src} buddy={buddy} "
            f"epoch={self.world.epoch}"
        )
        # 1) gossip/steal state: forget the dead peer, repoint targeted
        # directory entries at its buddy, release RFR/push state that
        # would otherwise block forever on a response that never comes
        self.peers.pop(dead, None)
        self.tq.repoint(dead, buddy)
        self._rfr_out.clear()
        for excluded in self._rfr_excluded.values():
            excluded.discard(dead)
        self._push_offered.clear()
        for qid in [q for q in self._push_reserved if (q >> 20) == dead]:
            self.mem.free(self._push_reserved.pop(qid))
        # 2) migration batches in transit TO the dead server: the units
        # serialized inside unacked SS_MIGRATE_WORK frames live in no wq
        # anywhere — take them back
        for tok, units in self._migrate_pending.pop(dead, {}).items():
            self._migrate_unacked -= 1
            for u in units:
                self._admit_migrated_unit(u, bounced=False)
            self.flight.record(
                f"migrate batch tok={tok} to dead server {dead} "
                f"requeued ({len(units)} units)"
            )
        held = getattr(self, "_held_checkpoints", None)
        if held and self._migrate_unacked == 0:
            self._held_checkpoints = []
            for h in held:
                self._process_checkpoint(h)
        # 3) our own replication stream: if the dead server was our
        # buddy, re-bootstrap toward the next live successor
        if self.repl is not None and self.repl.buddy == dead:
            self._rebootstrap_repl(
                replica.buddy_of(self.world, self.rank, self._buddy_excluded())
            )
        # 4) master: retire the dead server's snapshot so plans stop
        # naming it, and re-kick a possibly-lost END_1 token
        if self.is_master:
            if self.cfg.balancer == "tpu":
                self._snapshots.pop(dead, None)
                self._req_sigs.pop(dead, None)
                self._broadcast_hungry(self._hungry_tracker.update(dead, []))
                if self._balancer is not None:
                    self._balancer.wake.set()
            if not self.done and (self._ending or self._end1_pending) and (
                self._finalized >= self.local_apps
            ):
                self._end1_pending = True
                self._forward_end1(
                    {"origin": self.rank, "epoch": self.world.epoch}
                )
        # the topology change is activity: an exhaustion vote must not
        # conclude across it
        self.activity += 1
        self._exhaust_held_since = None
        # 5) off-home targeted inventory for ranks the buddy adopts: the
        # buddy's directory starts empty, so re-announce what WE hold
        if buddy != self.rank:
            # one pass over the wq (this runs inside the latency-critical
            # failover window; a rescan per announced pair would be
            # O(units x pairs))
            counts: dict[tuple[int, int], int] = {}
            for u in self.wq.units():
                if (
                    u.target_rank >= 0
                    and self.world.home_server(u.target_rank) == dead
                ):
                    key = (u.target_rank, u.work_type)
                    counts[key] = counts.get(key, 0) + 1
            for (t_rank, wtype), n in counts.items():
                try:
                    self.ep.send(
                        buddy,
                        msg(Tag.SS_MOVING_TARGETED_WORK, self.rank,
                            app_rank=t_rank, work_type=wtype,
                            from_server=dead, to_server=self.rank,
                            count=n),
                    )
                except OSError:
                    pass
        # 6) handoffs routed THROUGH the dead home server: units pinned
        # here for its app ranks went out as RFR/plan responses via the
        # dead home, so their resolution (SS_DELIVERED / UNRESERVE / the
        # client's fetch after an undelivered handle) may have died with
        # it. A fused relay's payload may already have been forwarded —
        # at-most-once wins (delivered-at-death, as in the rank-death
        # sweep); a handle-shaped handoff unpins so the unit re-matches
        # (an owner that DID receive the handle gets ADLB_RETRY on its
        # fetch and re-reserves).
        swept = 0
        for r in self.world.local_apps(dead):
            if r in self._dead_ranks:
                continue
            for lease in self.leases.owned_by(r):
                unit = self.wq.get(lease.seqno)
                if unit is None or not unit.pinned or unit.pin_rank != r:
                    continue
                if self._relay_inflight.get(lease.seqno) == r:
                    self._relay_inflight.pop(lease.seqno, None)
                    self._consume(unit)
                    self.flight.record(
                        f"relay_consumed_on_failover seqno={lease.seqno} "
                        f"rank={r} via={dead}"
                    )
                    continue
                self.leases.release(lease.seqno)
                self.wq.unpin(lease.seqno)
                if self.wlog is not None:
                    self.wlog.log_unpin(lease.seqno)
                if unit.common_seqno >= 0:
                    # the owner may have fetched the prefix already (the
                    # handle path orders common-first); the re-match
                    # fetches again — bounded-leak direction, as in the
                    # reclaim sweep
                    self._forfeit_common(
                        unit.common_seqno, unit.common_server_rank,
                        op="credit",
                    )
                swept += 1
        if swept:
            self.flight.record(
                f"unpinned {swept} handoffs routed via dead server {dead}"
            )
            self._match_rq()
        # 7) the buddy replays the mirror and takes over; held until the
        # dead server's own EOF drains its replication tail (bounded —
        # the death may predate any connection from it to us)
        if buddy == self.rank:
            if dead in self._server_tail_drained:
                self._promote(dead)
            else:
                self._pending_promotion[dead] = time.monotonic() + 2.0
        # parked requesters whose RFRs died with the server re-arm
        for entry in self.rq.entries():
            if entry.world_rank not in self._rfr_out:
                self._try_rfr(entry)

    def _admit_migrated_unit(self, u: dict, bounced: bool) -> None:
        """Install one migrated-unit record into the local wq (shared by
        the normal SS_MIGRATE_WORK intake and the dead-destination
        requeue). Admission control only on first sight; a unit already
        admitted to the system is never dropped."""
        self.mem.alloc(len(u["payload"]))
        unit = WorkUnit(
            seqno=self._next_seqno,
            work_type=u["work_type"],
            prio=u["prio"],
            target_rank=-1,
            answer_rank=u["answer_rank"],
            payload=u["payload"],
            home_server=u["home_server"],
            common_len=u["common_len"],
            common_server_rank=u["common_server"],
            common_seqno=u["common_seqno"],
            time_stamp=u["time_stamp"],
            attempts=int(u.get("attempts", 0) or 0),
            job=int(u.get("job", 0) or 0),
        )
        self._next_seqno += 1
        tf = u.get("trace")
        if tf:
            self.journeys.adopt(unit, tf["id"], tf["spans"],
                                stage="migrate")
        self.wq.add(unit)
        if self.wlog is not None:
            self.wlog.log_put(unit, -1, None)
        self.stats[InfoKey.NPUSHED_TO_HERE] += 1

    # -- takeover (buddy side) ----------------------------------------------

    def _promote(self, dead: int) -> None:
        """Replay the dead predecessor's mirrored shard into this
        server's live queues and take over home-server duty for its app
        ranks."""
        if self.done:
            return
        clean = dead in self._clean_retire
        mirror = self.mirrors.pop(dead, None)
        if mirror is None:
            if clean:
                # a drained server with nothing to ship (it flushed an
                # EMPTY full-state bootstrap): promote a blank mirror
                from adlb_tpu.runtime import replica

                mirror = replica.ReplicaMirror(dead)
            else:
                # double failure: the shard died with its buddy before
                # any replication frame reached us — unrecoverable
                aprintf(
                    True, self.rank,
                    f"server rank {dead} died but no replica of its "
                    f"shard exists here (buddy died before promotion?); "
                    f"aborting",
                )
                self._do_abort(-3, broadcast=True)
                return
        mirror.seal()
        t0 = self._server_eof_at.get(dead, time.monotonic())
        # computed BEFORE any mutation: succession (set_master below)
        # rewrites what master_server_rank answers
        was_master = dead == self.world.master_server_rank
        # 1) batch-common prefixes first (units reference them)
        for old_cseq, (buf, refcnt, ngets, credits) in sorted(
            mirror.commons.items()
        ):
            self.mem.alloc(len(buf))
            new_cseq = self.cq.adopt(buf, refcnt, ngets, credits)
            self._adopted_commons[(dead, old_cseq)] = new_cseq
            if self.wlog is not None:
                self.wlog.log_common_put(new_cseq, buf)
                self.wlog.log_common_state(new_cseq, refcnt, ngets, credits)
        # 2) units: pinned-to-a-live-client survive PINNED under their
        # lease behind a seqno translation (the client's in-flight fetch
        # lands here via the fo_from reroute); everything else re-enqueues
        adopted = pinned_kept = lost = 0
        hedge_dropped = 0
        for old_seqno in sorted(mirror.units):
            if old_seqno in mirror.hedges:
                # live hedge SIBLING at takeover: its origin is in this
                # same mirror and adopts normally — adopting the sibling
                # too would hand the new home two live duplicates with
                # no group state to fence the loser. Drop the sibling
                # (not a counted loss: the logical put survives via the
                # origin) and FENCE its pinned owner, so the rerouted
                # late fetch answers ADLB_FENCED (you lost the race —
                # re-reserve) instead of a miscounted failover loss.
                pin_rank = mirror.pins.get(old_seqno, -1)
                if pin_rank >= 0:
                    self._adopted_fences.add((dead, old_seqno, pin_rank))
                    if self.wlog is not None:
                        self.wlog.log_fence(old_seqno, pin_rank,
                                            origin=dead)
                hedge_dropped += 1
                continue
            f = mirror.units[old_seqno]
            pin_rank = mirror.pins.get(old_seqno, -1)
            target = f["target_rank"]
            cs, cseq = f["common_server_rank"], f["common_seqno"]
            clen = f["common_len"]
            if cseq >= 0 and cs == dead:
                new_c = self._adopted_commons.get((dead, cseq))
                if new_c is None:
                    # prefix lost to replication lag: the suffix alone is
                    # not the unit — counted ONCE here (registered so the
                    # pin owner's later fetch answers RETRY uncounted)
                    lost += 1
                    self._counted_lost.add((dead, old_seqno))
                    self._m_failover_lost.inc()
                    if f.get("trace_id"):
                        # failover loss is terminal for the journey too
                        self.journeys.close_spans(
                            f["trace_id"], f.get("job", 0),
                            f["work_type"], "lost",
                            list(f.get("spans") or []),
                        )
                    self.flight.record(
                        f"failover_lost unit={old_seqno} (prefix gone)"
                    )
                    continue
                cs, cseq = self.rank, new_c
            if target >= 0 and (
                target in self._dead_ranks or target in mirror.dead_ranks
            ):
                self._m_targeted_dropped.inc()
                self._forfeit_common(cseq, cs)
                continue
            if pin_rank >= 0 and pin_rank in self._dead_ranks:
                # owner died before its home server did: reclaim rules
                pin_rank = -1
                if cseq >= 0:
                    self._forfeit_common(cseq, cs, op="credit")
            unit = WorkUnit(
                seqno=self._next_seqno,
                work_type=f["work_type"],
                prio=f["prio"],
                target_rank=target,
                answer_rank=f["answer_rank"],
                payload=f["payload"],
                home_server=self.rank,
                common_len=clen,
                common_server_rank=cs,
                common_seqno=cseq,
                pinned=pin_rank >= 0,
                pin_rank=pin_rank if pin_rank >= 0 else -1,
                attempts=f.get("attempts", 0),
                job=f.get("job", 0),
            )
            self._next_seqno += 1
            self.mem.alloc(len(unit.payload))
            if f.get("trace_id"):
                # the journey survives the takeover with an "adopt" hop
                # (and rides our own wlog onward via log_put below);
                # clean drains stamp "drain" instead, so scale-in churn
                # is visible in /trace/tails
                self.journeys.adopt(unit, f["trace_id"], f.get("spans"),
                                    stage="drain" if clean else "adopt")
            self.wq.add(unit)
            if pin_rank >= 0:
                self.leases.grant(unit.seqno, pin_rank)
                self._adopted_units[(dead, old_seqno)] = unit.seqno
                pinned_kept += 1
            adopted += 1
            if self.wlog is not None:
                self.wlog.log_put(unit, -1, None)
        # 3) tombstones: a post-takeover fetch of a consumed unit is a
        # counted loss (the response died with the server), not an
        # invalid-handle abort
        self._adopted_tombs.update((dead, s) for s in mirror.tombstones)
        # ... fencing state rides the stream too: a fenced owner's
        # rerouted late fetch must stay rejected (ADLB_FENCED), never be
        # miscounted as a replication-lag loss or — worse — served. A
        # fence's key is the numbering of the ORIGINAL home (reroutes
        # stamp fo_from with it), so fences the dead server had itself
        # adopted (origin >= 0) keep their origin through the chain —
        # and every adopted fence is logged onward to OUR buddy so a
        # THIRD takeover still rejects the doubly-rerouted fetch
        for (s, o, origin) in mirror.fences:
            key = (dead if origin < 0 else origin, s, o)
            self._adopted_fences.add(key)
            if self.wlog is not None:
                self.wlog.log_fence(s, o, origin=key[0])
        # ... and the predecessor's dead-letter quarantine: re-homed
        # under fresh seqnos and re-counted HERE (its own QUARANTINED
        # stat died with it — only the survivor's count reaches the
        # final aggregation, keeping the conservation total exact)
        for old_seqno in sorted(mirror.quarantined):
            self._adopt_quarantined(mirror.quarantined[old_seqno],
                                    old_seqno, dead)
        # 4) duplicate-put protection survives the failover: the dead
        # server's accepted-put windows merge, so a client re-sending an
        # acked-but-unanswered put gets the idempotent ack, not a dup unit
        for src, ids in mirror.seen_puts.items():
            for pid in ids:
                self._put_record(src, pid)
        # ... and the common-prefix dedup identities: a get/forfeit the
        # dead server already accounted (and replicated) re-sent toward
        # this buddy must be absorbed, not double-accounted against the
        # adopted refcount state. Ids are per-client monotonic, so the
        # newest wins for the last-get check.
        for src, gid in mirror.last_common.items():
            if gid > self._last_common.get(src, -1):
                self._last_common[src] = gid
        for src, fids in mirror.forfeit_ids.items():
            for fid in fids:
                self._window_seen(self._seen_forfeits, src, fid)
        # 5) home-server duty: adopt the dead server's app ranks (with
        # their finalize/death accounting)
        newly = set(self.world.local_apps(dead))
        self.local_apps |= newly
        # job lifecycle the predecessor knew (normally already here via
        # the SS_JOB_CTL fan-out; the replay makes it exact even when a
        # fan-out frame died with the server)
        for jid, (code, quota, jname) in mirror.jobs_meta.items():
            if self.jobs.get(jid) is None:
                self.jobs.restore(jid, code, quota, jname)
        self._finalized |= mirror.finalized & newly
        for r in mirror.dead_ranks:
            self._dead_ranks.add(r)
            self._swept_streams.add(r)
            if r in self.local_apps:
                self._finalized.add(r)
        # adopted ranks' streams may hold phantom slots (reserves parked
        # at the dead server): their next idle note re-arms them
        self._swept_streams |= newly
        if was_master and not clean:
            # the dead server was the BRAIN: restore the replicated
            # control plane, take the master role under a bumped epoch,
            # and fan the succession before any termination verdict can
            # conclude (the takeover barrier gates exhaustion/END)
            self._promote_master(dead, mirror, t0)
        mttr_ms = (time.monotonic() - t0) * 1e3
        if not clean:
            # a drain is not a failover: the promote machinery is shared
            # but the death metrics (and their acceptance oracles —
            # "zero failover_lost, zero failovers on a clean scale-in")
            # stay death-only
            self._m_failover_promoted.inc()
            self._g_fo_mttr.set(mttr_ms)
        self.activity += 1
        self._exhaust_held_since = None
        self.flight.record(
            f"failover_promoted dead={dead} adopted_units={adopted} "
            f"pinned_kept={pinned_kept} lost={lost} "
            f"hedge_siblings_dropped={hedge_dropped} "
            f"commons={len(mirror.commons)} ranks={sorted(newly)} "
            f"mttr_ms={mttr_ms:.1f}"
        )
        aprintf(
            True, self.rank,
            f"took over server {dead}: {adopted} units "
            f"({pinned_kept} pinned), {len(mirror.commons)} common "
            f"prefixes, app ranks {sorted(newly)}, mttr {mttr_ms:.1f} ms",
        )
        # 6) epoch-stamped remap: every live app learns the new home /
        # routing (finished apps' listeners may be gone — best-effort,
        # short connect grace)
        note = dict(dead=dead, epoch=self.world.epoch)
        if was_master and not clean:
            # clients re-point job control / detach / checkpoint asks at
            # the promoted deputy (the srv_route reroute alone would
            # only cover traffic addressed to the DEAD rank)
            note["new_master"] = self.rank
        for r in self.world.app_ranks:
            if r in self._dead_ranks:
                continue
            try:
                self.ep.send(
                    r, msg(Tag.TA_HOME_TAKEOVER, self.rank, **note),
                    connect_grace=1.0,
                )
            except OSError:
                pass
        # the one-shot fan-out above is best-effort; re-announce from the
        # periodic tick until every client's failover window has closed
        # (the client-side apply is idempotent — duplicate notes no-op)
        self._takeover_renotify[dead] = (
            time.monotonic() + self.cfg.failover_client_wait
        )
        self.flight.dump_json(f"failover_{dead}")
        # the adopted shard may satisfy parked requesters right now; and
        # if every adopted rank already finalized, termination proceeds
        self._match_rq()
        self._maybe_complete_finalize()
        if self.cfg.balancer == "tpu":
            self._send_snapshot()

    # -- master succession (deputy side) --------------------------------------

    def _promote_master(self, dead: int, mirror, t0: float) -> None:
        """The dead server was the MASTER and this buddy is its standing
        deputy. Restore the replicated brain (durable control plane),
        take the master role under a bumped fleet epoch, rebuild the
        reconstructed engines (SLO/controller under a churn hold, so
        pre-death alerts re-enter without re-firing), restart the
        balancer, rebind the ops endpoint, and fan the epoch-stamped
        succession behind an ack barrier (exhaustion/END defer on it)."""
        now = time.monotonic()
        brain = getattr(mirror, "brain", None) or {}
        # 1) durable brain state — applied BEFORE set_master, since the
        # snapshot still names the dead master (epoch-guarded)
        self.world.seed(brain.get("member") or {})
        self._member_next_rank = max(
            self._member_next_rank, int(brain.get("next_rank", 0) or 0)
        )
        for r, a in (brain.get("addrs") or {}).items():
            r = int(r)
            self._member_addrs.setdefault(r, tuple(a))
            if hasattr(self.ep, "addr_map"):
                self.ep.addr_map.setdefault(r, tuple(a))
        for s in brain.get("live") or ():
            if s != self.rank and s not in self._dead_servers:
                self._member_live.add(int(s))
        for s in brain.get("ready") or ():
            if s not in self._dead_servers:
                self._member_ready.add(int(s))
        for s in brain.get("drained") or ():
            self._drained_servers.add(int(s))
            self._dead_servers.add(int(s))
            self._clean_retire.add(int(s))
        for r, b in (brain.get("srv_route") or {}).items():
            self._srv_route.setdefault(int(r), int(b))
        self._job_next_id = max(
            self._job_next_id, int(brain.get("job_next_id", 1) or 1)
        )
        weights = dict(getattr(mirror, "job_weights", None) or {})
        for jid, w in weights.items():
            self.jobs.apply("update", int(jid), weight=float(w))
        if weights:
            self._pending_job_weights = self._effective_job_weights()
        # 2) succession under a bumped epoch: every in-flight
        # exhaustion/END token (the dead master's included) now carries
        # a stale epoch and voids at the first live hop
        epoch = max(self.world.epoch, int(brain.get("epoch", 0) or 0)) + 1
        self.world.set_master(self.rank, epoch)
        self.is_master = True
        self.flight.context["is_master"] = True
        self._g_epoch.set(self.world.epoch)
        # 3) reconstructed engines. The obs plane heals itself: every
        # server's next SS_OBS_SYNC targets master_server_rank — us —
        # and gossip snapshots are cumulative, so the merged fleet view
        # converges within one sync interval.
        armed = bool(brain.get("ops_armed")) or (
            self.cfg.ops_port is not None
        )
        if armed and self.cfg.obs_sync_interval > 0:
            if not self._obs_sync_armed:
                self._obs_sync_armed = True
                self._next_obs_sync = now + self.cfg.obs_sync_interval
            slo_docs = list(
                (getattr(mirror, "slo_docs", None) or {}).values()
            )
            if slo_docs or self.cfg.slo or self._slo_engine is not None:
                from adlb_tpu.obs.slo import SloEngine

                if self._slo_engine is None:
                    eng = SloEngine(
                        self.cfg.slo_eval_interval
                        or self.cfg.obs_sync_interval
                    )
                    for doc in self.cfg.slo or ():
                        try:
                            eng.add(doc)
                        except ValueError:
                            pass
                    self._slo_engine = eng
                for doc in slo_docs:
                    try:
                        self._slo_engine.add(doc)
                    except ValueError:
                        pass  # config duplicate: already installed
                # churn hold: alert lifecycles re-enter quietly — the
                # takeover transient must not re-fire a page
                self._slo_engine.note_epoch(
                    int(brain.get("epoch", 0) or 0), now
                )
                self._slo_engine.note_epoch(self.world.epoch, now)
        pol = getattr(mirror, "control_policy", None)
        if self._controller is None and (pol or self.cfg.control):
            from adlb_tpu.control import Controller

            self._controller = Controller(
                {
                    "dry_run": self.cfg.control_dry_run,
                    "min_servers": self.cfg.control_min_servers,
                    "max_servers": self.cfg.control_max_servers,
                    "cooldown_s": self.cfg.control_cooldown_s,
                    "scaleout_pressure": self.cfg.control_scaleout_pressure,
                    "scalein_pressure": self.cfg.control_scalein_pressure,
                },
                eval_interval=(self.cfg.control_interval
                               or self.cfg.obs_sync_interval),
            )
        if self._controller is not None:
            if pol:
                try:
                    self._controller.update_policy(dict(pol))
                except ValueError:
                    pass
            self._controller.note_epoch(
                int(brain.get("epoch", 0) or 0), now
            )
            self._controller.note_epoch(self.world.epoch, now)
        if (
            getattr(mirror, "scale_pending", None) is not None
            and self._scale_pending is None
        ):
            self._scale_pending = dict(mirror.scale_pending)
        # 4) the balancer brain restarts here, against the snapshot
        # store the gossip refills (and the _send_snapshot at the end
        # of _promote primes with our own inventory)
        if self.cfg.balancer == "tpu" and self._balancer is None:
            self._balancer = _BalancerWorker(self)
            self._balancer.start()
        # 5) ops endpoint rebind: always an EPHEMERAL port — the dead
        # master's HTTP thread may still hold cfg.ops_port (in-proc
        # death is a connectivity fault, not a process exit). The new
        # port travels in the takeover frame and the rendezvous dir.
        if armed and self.ops is None:
            from adlb_tpu.obs.ops_server import maybe_start

            self.ops = maybe_start(self, self.cfg, port=0)
        self._announce_ops_endpoint()
        # 6) succession fan-out behind an ack barrier
        self._master_takeover_fan()
        mttr = (now - t0) * 1e3
        # lazily minted: only a world that actually promoted a master
        # carries the row (frame identity for everyone else)
        self.metrics.gauge("master_failover_mttr_ms").set(mttr)
        self.flight.record(
            f"master_takeover dead={dead} epoch={self.world.epoch} "
            f"mttr_ms={mttr:.1f} slo={len(self._slo_engine.objectives) if self._slo_engine else 0} "
            f"control={'y' if self._controller else 'n'} "
            f"ops_port={self.ops.port if self.ops else None}"
        )
        aprintf(
            True, self.rank,
            f"promoted to master (epoch {self.world.epoch}, "
            f"mttr {mttr:.1f} ms)",
        )
        # 7) this new master's own buddy is the NEXT deputy: ship it the
        # whole brain so sequential master deaths keep succeeding
        if self.repl is not None:
            self._repl_brain()
            if self._slo_engine is not None:
                for o in self._slo_engine.objectives:
                    self.repl.log_slo(dict(o))
            if self._controller is not None:
                self.repl.log_control(self._controller.policy_doc())
            if self._scale_pending is not None:
                self.repl.log_scale(dict(self._scale_pending))
            for j in self.jobs.values():
                if j.weight != 1.0:
                    self.repl.log_job_weight(j.job_id, j.weight)

    def _announce_ops_endpoint(self) -> None:
        """Publish the live ops endpoint to Config(ops_announce_dir):
        the out-of-band rendezvous an HTTP consumer polls across a
        succession (the old port dies with the old master)."""
        d = self.cfg.ops_announce_dir
        if not d or self.ops is None:
            return
        try:
            import json as _json
            import os as _os

            tmp = _os.path.join(d, ".ops_endpoint.tmp")
            with open(tmp, "w") as f:
                _json.dump({
                    "host": "127.0.0.1",
                    "port": self.ops.port,
                    "master": self.rank,
                    "epoch": self.world.epoch,
                }, f)
            _os.replace(tmp, _os.path.join(d, "ops_endpoint.json"))
        except OSError:
            pass  # rendezvous is best-effort; the takeover frame is not

    def _master_takeover_fan(self) -> None:
        """Fan SS_MASTER_TAKEOVER to every live server behind an ack
        barrier (the membership-barrier shape): until every survivor
        acks the new epoch, no exhaustion vote starts here and no END
        ring kicks — the no-raced-verdict guarantee."""
        self._takeover_tok += 1
        tok = self._takeover_tok
        fields = dict(
            new_master=self.rank, epoch=self.world.epoch,
            member_tok=tok,
        )
        if self.ops is not None:
            fields["host"], fields["port"] = "127.0.0.1", self.ops.port
        need = set()
        for s in self._live_servers():
            try:
                self.ep.send(
                    s, msg(Tag.SS_MASTER_TAKEOVER, self.rank, **fields)
                )
                need.add(s)
            except OSError:
                self._note_server_unreachable(s)
        if need:
            self._takeover_pending = {
                "need": need, "tok": tok,
                "deadline": time.monotonic() + 5.0,
            }
        else:
            self._master_takeover_done()

    def _master_takeover_done(self) -> None:
        self._takeover_pending = None
        self.activity += 1
        self._exhaust_held_since = None
        # re-initiate the termination ring: an END token the dead master
        # originated died with it (or voids on the bumped epoch); if the
        # world was terminating, this master re-kicks under the new epoch
        if (
            not self.done and (self._ending or self._end1_pending)
            and self._finalized >= self.local_apps
        ):
            self._end1_pending = True
            self._forward_end1(
                {"origin": self.rank, "epoch": self.world.epoch}
            )
        else:
            self._maybe_complete_finalize()

    def _on_master_takeover(self, m: Msg) -> None:
        if m.data.get("mop") == "ack":
            p = self._takeover_pending
            if p is None or m.data.get("member_tok") != p["tok"]:
                return
            p["need"].discard(m.src)
            if not p["need"]:
                self._master_takeover_done()
            return
        new_master = int(m.data["new_master"])
        epoch = int(m.data.get("epoch", 0) or 0)
        self.world.set_master(new_master, epoch)
        self._g_epoch.set(self.world.epoch)
        self.flight.record(
            f"master_takeover_seen new_master={new_master} "
            f"epoch={epoch} ops_port={m.data.get('port')}"
        )
        # the succession is activity (a held exhaustion vote must not
        # conclude across it) and voids any stale-epoch token we relay
        self.activity += 1
        self._exhaust_held_since = None
        tok = m.data.get("member_tok")
        if tok:
            try:
                self.ep.send(
                    m.src, msg(Tag.SS_MASTER_TAKEOVER, self.rank,
                               mop="ack", member_tok=tok)
                )
            except OSError:
                pass

    # -- takeover translation (content-addressed messages) --------------------

    def _adopted_unit_for(self, m: Msg):
        """Resolve a rerouted message's (dead server, old seqno) to the
        adopted local seqno; None when the unit did not survive."""
        return self._adopted_units.get((m.data["fo_from"], m.seqno))

    def _adopted_common_for(self, fo_from: int, cseq: int):
        return self._adopted_commons.get((fo_from, cseq))

    # ------------------------------------------------------- abort / watchdog

    def _on_fa_abort(self, m: Msg) -> None:
        self._do_abort(m.data.get("code", -1), broadcast=True)

    def _on_ss_abort(self, m: Msg) -> None:
        self._do_abort(m.data.get("code", -1), broadcast=False)

    def _do_abort(self, code: int, broadcast: bool) -> None:
        if self._aborted:
            return
        self._aborted = True
        aprintf(self.cfg.aprintf_flag, self.rank, f"aborting, code {code}")
        # the reference dumps every server's state on abort with a grace
        # period (src/adlb.c:2508-2526); here: the in-memory flight recorder
        self.flight.record(f"abort code={code} broadcast={broadcast}")
        self.flight.dump(reason=f"abort {code}")
        if broadcast:
            for srv in self.world.server_ranks:
                if srv == self.rank or srv in self._dead_servers:
                    continue
                try:
                    self.ep.send(srv, msg(Tag.SS_ABORT, self.rank, code=code))
                except OSError:
                    pass  # already-dead peer must not block the abort
        for app in self.local_apps:
            if app in self._dead_ranks:
                continue  # no listener left; a connect-retry would stall
            try:
                self.ep.send(app, msg(Tag.TA_ABORT, self.rank, code=code))
            except OSError:
                pass  # already-dead client: the abort_event reaches it
        if self._abort_event is not None:
            self._abort_event.set()
        self.done = True

    def _send_ds_log(self) -> None:
        """The reference's 11-counter heartbeat (``log_at_debug_server``,
        reference ``src/adlb.c:3222-3259``): since-last-log event counts
        plus point-in-time queue depths. The iq and unexpected-queue
        fields map to the transport backlog (received-but-unhandled
        frames); the memory probe is /proc RSS."""
        ds = self.world.debug_server_rank
        if ds is None:
            return
        events = sum(self.tag_freq.values())
        ss = sum(
            n for t, n in self.tag_freq.items() if t.name.startswith("SS_")
        )
        # self_diagnosis clears tag_freq on its own cadence; a counter
        # that went backwards means a reset, so the delta restarts from 0
        if events < self._ds_last["events"] or ss < self._ds_last["ss"]:
            self._ds_last["events"] = 0
            self._ds_last["ss"] = 0
        wq_targeted = sum(
            1 for u in self.wq.units() if u.target_rank >= 0
        )
        last = self._ds_last
        from adlb_tpu.utils.stats import rss_kb

        self.ep.send(
            ds,
            msg(
                Tag.DS_LOG,
                self.rank,
                counters={"puts": self._m_puts.v, "reserves": self._m_reserves.v,
                          "rfrs": self._m_rfrs.v, "pushes": self._m_pushes.v},
                events=events - last["events"],
                wq_targeted=wq_targeted,
                wq_count=self.wq.count,
                rq_count=len(self.rq),
                backlog=self.ep.backlog()
                if hasattr(self.ep, "backlog") else 0,
                reserves=self.stats[InfoKey.NUM_RESERVES] - last["reserves"],
                reserves_immed=self._n_reserve_immed - last["immed"],
                reserves_parked=(
                    self.stats[InfoKey.NUM_RESERVES_PUT_ON_RQ]
                    - last["parked"]
                ),
                rfr_failed=self._n_rfr_failed - last["rfr_failed"],
                ss_msgs=ss - last["ss"],
                rss_kb=rss_kb(),
                nbytes=self.mem.curr,
            ),
        )
        self._ds_last = {
            "events": events,
            "ss": ss,
            "reserves": self.stats[InfoKey.NUM_RESERVES],
            "immed": self._n_reserve_immed,
            "parked": self.stats[InfoKey.NUM_RESERVES_PUT_ON_RQ],
            "rfr_failed": self._n_rfr_failed,
        }

    def _notify_debug_server_end(self) -> None:
        ds = self.world.debug_server_rank
        if ds is not None:
            self.ep.send(ds, msg(Tag.DS_END, self.rank))

    # ------------------------------------------------------- stats surface

    def finalize_stats(self) -> dict:
        from adlb_tpu.utils.stats import rss_kb

        s = self.stats
        s[InfoKey.MALLOC_HWM] = float(self.mem.hwm)
        s[InfoKey.RSS_KB] = float(rss_kb())
        s[InfoKey.NUM_FAILOVERS] = float(
            self.metrics.value("failover_promoted")
        )
        s[InfoKey.FAILOVER_LOST] = float(self.metrics.value("failover_lost"))
        s[InfoKey.FAILOVER_MTTR_MS] = float(self._g_fo_mttr.v)
        s[InfoKey.AVG_TIME_ON_RQ] = (
            self._rq_wait_sum / self._rq_wait_n if self._rq_wait_n else 0.0
        )
        return {int(k): float(v) for k, v in s.items()}
