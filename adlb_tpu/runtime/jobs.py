"""Job namespaces: multi-tenancy over one persistent server fleet.

The reference binds one world to one job to one process lifetime — the
pool has no namespace column, termination is world-global, and the only
way to run a second workload is a second fleet. Service mode multiplexes
*jobs* over the same servers:

* every wire frame may carry a ``job_id`` (codec field 97; omitted = the
  default namespace 0, so single-job worlds stay byte-identical on the
  wire);
* the work queue partitions per job (:class:`PartitionedWorkQueue`) and
  a requester only ever matches units of its own namespace;
* termination is per job: the master runs the two-pass exhaustion ring
  *per job* (token stamped with the job id), and a completed job's
  parked requesters are flushed with ``ADLB_DONE_BY_EXHAUSTION`` without
  touching any other job — one job draining never blocks another;
* admission is per tenant: a job's ``quota_bytes`` bounds its queued
  bytes per server, enforced at put with ``ADLB_BACKOFF`` +
  ``retry_after_ms`` (the PR 5 backpressure mechanism made per-job);
* the control plane is the ops endpoint's ``/jobs`` surface (submit /
  status / drain / kill) plus the in-band ``FA_JOB_CTL`` round trip that
  ``ctx.submit_job()`` / ``ctx.attach()`` use.

Lifecycle: RUNNING -> (drain) DRAINING -> DONE, or -> (kill) KILLED.
Draining rejects new puts (``ADLB_NO_MORE_WORK``) while queued work
completes; kill drops the job's partition outright and flushes its
parked requesters. State changes fan out as ``SS_JOB_CTL`` and ride the
replication stream / WAL as ``OP_JOB`` entries, so job lifecycle
survives failover and cold restart.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

RUNNING = "running"
DRAINING = "draining"
DONE = "done"
KILLED = "killed"

# wire/WAL state codes (replica.OP_JOB)
STATE_CODES = {RUNNING: 0, DRAINING: 1, DONE: 2, KILLED: 3}
CODE_STATES = {v: k for k, v in STATE_CODES.items()}

# job ids are small positive ints allocated by the master; 0 is the
# default/legacy namespace every world has implicitly
DEFAULT_JOB = 0


@dataclasses.dataclass
class Job:
    """One namespace's per-server view."""

    job_id: int
    name: str = ""
    state: str = RUNNING
    # per-server cap on this job's queued bytes (0 = unlimited): the
    # per-tenant admission quota — a put that would cross it answers
    # ADLB_BACKOFF with a retry-after hint, exactly the overload
    # backpressure discipline, scoped to the tenant
    quota_bytes: int = 0
    # fair-share weight (1.0 = neutral): folded into the balancer's
    # assignment score as a priority bias (balancer/jobdim.py) so a
    # heavy tenant cannot starve a light one. Fans out on SS_JOB_CTL;
    # deliberately NOT WAL-persisted (OP_JOB's fixed header predates
    # it) — a restarted fleet comes back neutral and the controller /
    # Config(job_weights) re-arms it.
    weight: float = 1.0
    submitted_at: float = dataclasses.field(default_factory=time.monotonic)
    done_at: Optional[float] = None
    # per-job activity (puts admitted + reservations matched), the
    # per-job analogue of Server.activity the exhaustion double-pass
    # compares across its two rings
    activity: int = 0
    # per-job exhaustion-ring state (master only)
    exhaust_held_since: Optional[float] = None
    exhaust_inflight: bool = False
    exhaust_sent_at: float = 0.0
    exhaust_token_id: int = 0
    # counters (per-server; the ops /jobs view reports the master's)
    puts: int = 0
    quarantined: int = 0
    backoffs: int = 0

    @property
    def accepts_puts(self) -> bool:
        return self.state == RUNNING

    @property
    def closed(self) -> bool:
        return self.state in (DONE, KILLED)

    def summary(self) -> dict:
        return {
            "job_id": self.job_id,
            "name": self.name,
            "state": self.state,
            "quota_bytes": self.quota_bytes,
            "weight": self.weight,
            "submitted_at": self.submitted_at,
            "done_at": self.done_at,
            "puts": self.puts,
            "quarantined": self.quarantined,
            "backoffs": self.backoffs,
        }


class JobTable:
    """job_id -> :class:`Job`, one per server. Lazily creating an entry
    on first sight of an unknown id absorbs the race between a client's
    first frame and the master's SS_JOB_CTL fan-out landing here."""

    def __init__(self) -> None:
        self._jobs: dict[int, Job] = {}

    def get(self, job_id: int) -> Optional[Job]:
        return self._jobs.get(job_id)

    def ensure(self, job_id: int, name: str = "",
               quota_bytes: int = 0) -> Job:
        job = self._jobs.get(job_id)
        if job is None:
            job = self._jobs[job_id] = Job(
                job_id=job_id, name=name, quota_bytes=quota_bytes
            )
        return job

    def apply(self, op: str, job_id: int, name: str = "",
              quota_bytes: int = 0,
              weight: Optional[float] = None) -> Job:
        """One SS_JOB_CTL/OP_JOB state transition; idempotent."""
        job = self.ensure(job_id, name=name, quota_bytes=quota_bytes)
        if weight is not None:
            job.weight = float(weight)
        if op == "submit":
            # re-announce of a live job refreshes quota/name only
            job.name = name or job.name
            if quota_bytes:
                job.quota_bytes = quota_bytes
        elif op == "update":
            # live policy tweak (POST /jobs/<id> or the controller):
            # weight handled above; quota 0 means "leave unchanged"
            # here (use kill/drain to end a tenant, not quota 0) —
            # the controller clears a throttle by restoring the
            # remembered pre-throttle quota, which is never 0 unless
            # it was unlimited, in which case -1 encodes "unlimited"
            if quota_bytes == -1:
                job.quota_bytes = 0
            elif quota_bytes:
                job.quota_bytes = quota_bytes
        elif op == "drain":
            if not job.closed:
                job.state = DRAINING
        elif op == "done":
            if job.state != KILLED:
                job.state = DONE
                job.done_at = time.monotonic()
        elif op == "kill":
            job.state = KILLED
            job.done_at = time.monotonic()
        else:
            raise ValueError(f"unknown job ctl op {op!r}")
        return job

    def restore(self, job_id: int, state_code: int, quota_bytes: int,
                name: str) -> Job:
        """WAL/replica replay: install the logged state directly."""
        job = self.ensure(job_id, name=name, quota_bytes=quota_bytes)
        job.state = CODE_STATES.get(state_code, RUNNING)
        job.name = name or job.name
        job.quota_bytes = quota_bytes
        return job

    def active_ids(self) -> list[int]:
        """Jobs whose termination the master still owes a verdict."""
        return [
            j.job_id for j in self._jobs.values()
            if j.job_id != DEFAULT_JOB and not j.closed
        ]

    def max_id(self) -> int:
        """Highest job id this table has ever seen — the id allocator
        must stay above it across WAL recovery / takeover replay, or a
        post-restart submit would reuse (and inherit the state of) a
        prior tenant's namespace."""
        return max(self._jobs, default=0)

    def weights(self) -> dict[int, float]:
        """Non-neutral fair-share weights, {job_id: weight} — the
        balancer's bias input (balancer/jobdim.bias_vector)."""
        return {
            j.job_id: j.weight for j in self._jobs.values()
            if j.weight != 1.0
        }

    def any_jobs(self) -> bool:
        """True once any non-default namespace exists — the switch that
        turns WORLD-level exhaustion off (service mode: the fleet idles
        between jobs instead of declaring the world done)."""
        return any(j != DEFAULT_JOB for j in self._jobs)

    def values(self) -> list[Job]:
        return list(self._jobs.values())

    def __len__(self) -> int:
        return len(self._jobs)

    def __contains__(self, job_id: int) -> bool:
        return job_id in self._jobs
