"""Multiplexed cross-host channels: one socket per host-pair, not per
rank-pair.

The per-pair TCP plane (transport_tcp.py) holds one persistent socket
per communicating rank pair — O(pairs) kernel state and one syscall per
small frame. At fleet scale (ROADMAP item 5: 1,000 servers) that is the
floor the balancer work cannot touch. This module collapses it:

* every rank on a host attaches to that host's **channel broker** over
  ONE socket and sends ``(src, dst, frame)`` envelopes;
* brokers hold one **bridge** channel per remote host, so the fleet's
  data plane is O(ranks + hosts^2) sockets instead of O(ranks^2);
* per-channel **send queues coalesce**: a writer drains everything
  queued into one ``sendmsg``, so a burst of N small frames costs O(1)
  syscalls (and, with the endpoint's submit batch, O(1) wakeups);
* DATA envelope bodies at least ``Config(compress_min_bytes)`` long are
  **zlib-compressed** end to end (flag bit 0 of the envelope header;
  brokers forward envelopes verbatim and never inflate).

Envelope wire format (after a u32 length prefix covering the rest):

    u8 etype    1 = DATA, 2 = ATTACH, 3 = DETACH, 4 = BRIDGE
    DATA:   u8 flags (bit 0: body zlib-compressed), i32 src, i32 dst,
            then the frame body (the same first-byte-discriminated
            pickle/TLV body the per-pair plane carries)
    ATTACH: i32 rank   (a rank binding this connection)
    DETACH: i32 rank   (rank gone: clean close or death)
    BRIDGE: utf-8 host key (a remote broker binding this connection)

Failure semantics — the per-pair death sentinel, preserved by
construction: a rank's process death EOFs its broker connection; the
broker broadcasts ``DETACH(rank)`` (to local ranks and every bridge,
AFTER the rank's already-read frames — same reader thread, so per-pair
ordering holds), and each endpoint that has seen traffic from that rank
synthesizes the same in-order ``PEER_EOF`` the per-pair reader would
have — every failure-policy ladder (reclaim, failover, lease fencing,
shm-hello sentinels) runs unchanged over the mux. A broker's own death
EOFs every attached rank, which synthesizes ``PEER_EOF`` for every peer
it had heard from — the host-died signal.

Native (C/Fortran) peers never ride channels: they speak raw
length-prefixed TLV on direct per-pair sockets, and the endpoint routes
``binary_peers`` around the mux.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import zlib
from collections import deque
from typing import Optional

E_DATA = 1
E_ATTACH = 2
E_DETACH = 3
E_BRIDGE = 4

_U32 = struct.Struct("<I")
_DATA_HDR = struct.Struct("<IBBii")  # elen, etype, flags, src, dst
_RANK_ENV = struct.Struct("<IBi")    # elen, etype, rank
DATA_OVERHEAD = _DATA_HDR.size - _U32.size  # etype+flags+src+dst

FLAG_COMPRESSED = 0x01

def _read_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    """TcpEndpoint._read_exact with OSError folded into the None (EOF)
    outcome — one exact-read implementation, like _send_gather below."""
    from adlb_tpu.runtime.transport_tcp import TcpEndpoint

    try:
        return TcpEndpoint._read_exact(conn, n)
    except OSError:
        return None


def _send_gather(sock: socket.socket, parts: list) -> None:
    """One frame-burst as gather writes — exactly TcpEndpoint._send_iov
    (IOV_MAX chunking, short-write resume at the unsent offset, EINTR
    resume, no-sendmsg fallback), imported so the wire discipline has
    ONE implementation. transport_tcp imports this module lazily, so the
    top-level import here creates no cycle."""
    from adlb_tpu.runtime.transport_tcp import TcpEndpoint

    TcpEndpoint._send_iov(sock, parts)


def data_envelope(src: int, dst: int, parts: list, nbody: int,
                  compress_min: int = 0) -> tuple[list, int]:
    """Build one DATA envelope as an iovec (header + body parts);
    returns (iovec, bytes_saved_by_compression)."""
    saved = 0
    if compress_min > 0 and nbody >= compress_min:
        z = zlib.compress(b"".join(bytes(p) for p in parts), 1)
        if len(z) < nbody:
            saved = nbody - len(z)
            hdr = _DATA_HDR.pack(DATA_OVERHEAD + len(z), E_DATA,
                                 FLAG_COMPRESSED, src, dst)
            return [hdr, z], saved
    hdr = _DATA_HDR.pack(DATA_OVERHEAD + nbody, E_DATA, 0, src, dst)
    return [hdr, *parts], saved


def rank_envelope(etype: int, rank: int) -> bytes:
    return _RANK_ENV.pack(5, etype, rank)


# ------------------------------------------------------------------ broker


class _BrokerConn:
    """One accepted connection (a local rank or a remote-broker bridge):
    a reader identity plus a coalescing send queue drained by a writer
    thread — a slow or dead peer never head-of-line-blocks the readers
    feeding it."""

    def __init__(self, broker: "ChannelBroker", sock: socket.socket) -> None:
        self.broker = broker
        self.sock = sock
        self.rank: Optional[int] = None        # set by ATTACH
        self.bridge_host: Optional[str] = None  # set by BRIDGE
        self.bridge_seen: set[int] = set()      # srcs seen over a bridge
        self._q: deque = deque()
        self._cv = threading.Condition()
        self.closed = False
        self._writer = threading.Thread(
            target=self._write_loop, daemon=True, name="adlb-chan-writer"
        )
        self._writer.start()

    def enqueue(self, env) -> None:
        """env: bytes, or an iovec list (header + body parts)."""
        with self._cv:
            if self.closed:
                return
            self._q.append(env)
            self._cv.notify()

    def _write_loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self.closed:
                    self._cv.wait()
                if self.closed and not self._q:
                    return
                batch, self._q = list(self._q), deque()
            parts: list = []
            for env in batch:
                if isinstance(env, (bytes, bytearray, memoryview)):
                    parts.append(env)
                else:
                    parts.extend(env)
            if len(batch) > 1:
                self.broker.frames_coalesced += len(batch) - 1
            try:
                _send_gather(self.sock, parts)
            except OSError:
                self.close()
                return

    def close(self) -> None:
        with self._cv:
            if self.closed:
                return
            self.closed = True
            self._cv.notify()
        try:
            self.sock.close()
        except OSError:
            pass


class ChannelBroker:
    """Per-host channel multiplexer. Local ranks attach with one socket
    each; remote brokers bridge with one socket per host-pair; DATA
    envelopes are forwarded verbatim by destination rank."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(256)
        self.addr: tuple[str, int] = self._listener.getsockname()
        self.hostkey = f"{self.addr[0]}:{self.addr[1]}"
        self._lock = threading.Lock()
        self.local: dict[int, _BrokerConn] = {}
        self.bridges: dict[str, _BrokerConn] = {}
        self._conns: list[_BrokerConn] = []
        # rank -> hostkey and hostkey -> broker addr, for multi-host
        # routing (single-host worlds never need them)
        self.rank_host: dict[int, str] = {}
        self.broker_addrs: dict[str, tuple[str, int]] = {}
        # frames for ranks that have not attached yet (the attach race:
        # rendezvous guarantees construction order, not byte order).
        # Bounded per destination: a rank that NEVER attaches (a native
        # peer mistakenly routed here, a misconfigured world) must not
        # grow memory forever — beyond the cap new frames drop like
        # bytes in flight, counted in frames_dropped
        self._pending: dict[int, list] = {}
        self.pending_cap = 4096
        self.frames_dropped = 0
        self._gone: set[int] = set()
        self._closed = False
        # observability (plain attributes: the broker lives in the
        # harness process, outside any rank's registry)
        self.frames_forwarded = 0
        self.frames_coalesced = 0
        self.peak_conns = 0
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="adlb-chan-broker").start()

    @property
    def conns_open(self) -> int:
        with self._lock:
            return sum(1 for c in self._conns if not c.closed)

    def set_routes(self, rank_host: dict[int, str],
                   broker_addrs: dict[str, tuple[str, int]]) -> None:
        """Teach this broker where non-local ranks live (multi-host
        worlds); hostkeys must match the remote brokers' ``hostkey``."""
        with self._lock:
            self.rank_host.update(rank_host)
            self.broker_addrs.update(broker_addrs)

    # -- accept/read ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _BrokerConn(self, sock)
            with self._lock:
                self._conns.append(conn)
                self.peak_conns = max(
                    self.peak_conns,
                    sum(1 for c in self._conns if not c.closed),
                )
            threading.Thread(target=self._read_loop, args=(conn,),
                             daemon=True, name="adlb-chan-reader").start()

    def _read_loop(self, conn: _BrokerConn) -> None:
        try:
            while True:
                hdr = _read_exact(conn.sock, 4)
                if hdr is None:
                    return
                (elen,) = _U32.unpack(hdr)
                payload = _read_exact(conn.sock, elen)
                if payload is None:
                    return
                et = payload[0]
                if et == E_DATA:
                    (dst,) = struct.unpack_from("<i", payload, 6)
                    if conn.bridge_host is not None:
                        (src,) = struct.unpack_from("<i", payload, 2)
                        conn.bridge_seen.add(src)
                    self._route(dst, hdr + payload)
                elif et == E_ATTACH:
                    (rank,) = struct.unpack_from("<i", payload, 1)
                    self._on_attach(conn, rank)
                elif et == E_DETACH:
                    (rank,) = struct.unpack_from("<i", payload, 1)
                    # forward a remote death to local ranks only (each
                    # broker fans out its own ranks' deaths — no loops)
                    self._broadcast_detach(rank, local_only=True)
                elif et == E_BRIDGE:
                    host = payload[1:].decode("utf-8", "replace")
                    conn.bridge_host = host
                    with self._lock:
                        self.bridges.setdefault(host, conn)
                # unknown envelope types are skipped, not fatal: the
                # protocol can grow (native daemons never attach here)
        finally:
            self._on_conn_eof(conn)

    # -- routing -------------------------------------------------------------

    def _route(self, dst: int, env) -> None:
        self.frames_forwarded += 1
        with self._lock:
            c = self.local.get(dst)
            if c is None:
                if dst in self._gone or self._closed:
                    return  # rank detached: drop, like bytes-in-flight
                host = self.rank_host.get(dst)
                if host is not None and host != self.hostkey:
                    bridge = self._bridge_locked(host)
                    if bridge is not None:
                        c = bridge
                if c is None:
                    backlog = self._pending.setdefault(dst, [])
                    if len(backlog) >= self.pending_cap:
                        self.frames_dropped += 1
                    else:
                        backlog.append(env)
                    return
        c.enqueue(env)

    def _bridge_locked(self, host: str) -> Optional[_BrokerConn]:
        """One outbound channel per remote host (caller holds _lock).

        The dial is synchronous under the broker lock: acceptable while
        bridges are harness-configured peers that are already listening
        (single-host worlds never dial at all); the multi-host launcher
        integration should move to an async dial + pending queue so a
        slow remote broker cannot stall local routing."""
        b = self.bridges.get(host)
        if b is not None and not b.closed:
            return b
        addr = self.broker_addrs.get(host)
        if addr is None:
            return None
        try:
            sock = socket.create_connection(addr, timeout=30)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            return None
        conn = _BrokerConn(self, sock)
        conn.bridge_host = host
        conn.enqueue(
            _U32.pack(1 + len(self.hostkey.encode()))
            + bytes([E_BRIDGE]) + self.hostkey.encode()
        )
        self.bridges[host] = conn
        self._conns.append(conn)
        self.peak_conns = max(
            self.peak_conns, sum(1 for c in self._conns if not c.closed)
        )
        threading.Thread(target=self._read_loop, args=(conn,),
                         daemon=True, name="adlb-chan-reader").start()
        return conn

    def _on_attach(self, conn: _BrokerConn, rank: int) -> None:
        # backlog flush and table publish are ONE atomic step under the
        # broker lock: a concurrently routed frame must either land in
        # the pending list (and flush here, in arrival order) or see the
        # published conn — never jump ahead of the backlog, or per-pair
        # ordering breaks for the attach window. conn.enqueue only takes
        # the conn's own cv, so no lock-order cycle.
        with self._lock:
            conn.rank = rank
            self._gone.discard(rank)
            for env in self._pending.pop(rank, []):
                conn.enqueue(env)
            self.local[rank] = conn

    def _broadcast_detach(self, rank: int, local_only: bool = False) -> None:
        env = rank_envelope(E_DETACH, rank)
        with self._lock:
            targets = [c for c in self._conns if not c.closed
                       and c.rank != rank
                       and (not local_only or c.bridge_host is None)]
        for c in targets:
            c.enqueue(env)

    def _on_conn_eof(self, conn: _BrokerConn) -> None:
        rank = conn.rank
        host = conn.bridge_host
        with self._lock:
            if rank is not None and self.local.get(rank) is conn:
                del self.local[rank]
                self._gone.add(rank)
            if host is not None and self.bridges.get(host) is conn:
                del self.bridges[host]
        conn.close()
        if self._closed:
            return
        if rank is not None:
            # the death sentinel: every channel learns this rank is gone
            self._broadcast_detach(rank)
        elif host is not None:
            # a whole remote host vanished: per-rank EOFs for every rank
            # whose traffic crossed this bridge
            for src in sorted(conn.bridge_seen):
                self._broadcast_detach(src, local_only=True)

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            c.close()


# ------------------------------------------------------------ rank client


class ChannelClient:
    """A rank's end of the channel plane: one socket to the local
    broker, envelopes out, frames + detach events in. Owned by (and
    plumbed into) a :class:`~adlb_tpu.runtime.transport_tcp.TcpEndpoint`
    — the endpoint keeps its listener for native per-pair peers and
    routes everything else here."""

    def __init__(self, ep, addr: tuple[str, int],
                 compress_min: int = 0) -> None:
        self._ep = ep
        self.compress_min = int(compress_min)
        self._sock = socket.create_connection(addr, timeout=30)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = threading.Lock()
        self.seen: set[int] = set()
        self.dead: set[int] = set()
        self.frames_coalesced = 0
        self._closed = False
        with self._wlock:
            self._sock.sendall(rank_envelope(E_ATTACH, ep.rank))
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"adlb-chan-client-{ep.rank}",
        )
        self._reader.start()

    # -- tx ------------------------------------------------------------------

    def send_batch(self, envs: list[list]) -> None:
        """One gather for a submit batch of prebuilt envelopes — the
        O(1)-syscalls burst path (see TcpEndpoint.submit_flush)."""
        if not envs:
            return
        if len(envs) > 1:
            self.frames_coalesced += len(envs) - 1
        parts: list = []
        for env in envs:
            parts.extend(env)
        with self._wlock:
            _send_gather(self._sock, parts)

    # -- rx ------------------------------------------------------------------

    def _read_loop(self) -> None:
        ep = self._ep
        try:
            while True:
                hdr = _read_exact(self._sock, 4)
                if hdr is None:
                    break
                (elen,) = _U32.unpack(hdr)
                payload = _read_exact(self._sock, elen)
                if payload is None:
                    break
                et = payload[0]
                if et == E_DATA:
                    flags, src = payload[1], struct.unpack_from(
                        "<i", payload, 2)[0]
                    body = payload[10:]
                    if flags & FLAG_COMPRESSED:
                        try:
                            body = zlib.decompress(body)
                        except zlib.error as e:
                            import sys

                            print(
                                f"[adlb chan rank {ep.rank}] dropping "
                                f"undecompressable envelope from {src}: "
                                f"{e!r}",
                                file=sys.stderr,
                            )
                            continue
                    if src in self.dead:
                        # traffic from a "dead" rank: the DETACH was
                        # connection churn (e.g. a bridge drop), not
                        # process death — resurrect, exactly like the
                        # server's _resurrect for per-pair churn EOFs
                        self.dead.discard(src)
                    self.seen.add(src)
                    ep._deliver_body(body, learn_binary=False)
                elif et == E_DETACH:
                    (rank,) = struct.unpack_from("<i", payload, 1)
                    self._peer_gone(rank)
        finally:
            # broker gone (or our own close): per-rank EOFs for every
            # peer we had heard from — the host-died ladder
            if not self._closed:
                for src in sorted(self.seen):
                    self._peer_gone(src)

    def _peer_gone(self, rank: int) -> None:
        from adlb_tpu.runtime.messages import Msg, Tag

        if rank in self.dead:
            return
        self.dead.add(rank)
        ep = self._ep
        if rank in self.seen and not ep._closed:
            ep.inbox.put(Msg(tag=Tag.PEER_EOF, src=rank))
            cb = ep.notify
            if cb is not None:
                cb()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def resolve_tcp_mux(cfg) -> bool:
    """Should a spawn_world-style single-host harness run the channel
    plane? An explicit ``Config(tcp_mux)`` wins; ``"auto"`` honors the
    ``ADLB_TCP_MUX`` env override (the CI leg's hook) and otherwise
    stays on per-pair TCP for single-host worlds (the mux pays two hops
    on loopback and wins exactly where the socket explosion lives —
    cross-host fleets)."""
    v = getattr(cfg, "tcp_mux", "auto")
    if v == "on":
        return True
    if v == "off":
        return False
    return os.environ.get("ADLB_TCP_MUX", "").strip().lower() in (
        "1", "on", "true", "yes"
    )
