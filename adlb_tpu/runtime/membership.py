"""Elastic membership: ranks and servers that join and leave a running
world.

The reference fixes every process role at ``ADLB_Init`` (PAPER.md
§"Process roles … fixed at ADLB_Init") — a world can only shrink by
dying. This module makes membership dynamic end to end, composing the
mechanisms earlier PRs built:

* **App ranks attach and detach** against a running fleet. Attach
  generalizes resurrection-without-a-prior-death: the MASTER allocates a
  fresh rank id and a home server under a new fleet epoch, fans the
  membership change to every server (``SS_MEMBER``), and only answers
  the joiner once every live server has acked — so by the time the new
  rank's first protocol frame lands anywhere, the whole fleet already
  counts it. Detach is a clean lease-draining rank-dead: the same
  fan-out/ack barrier removes the rank from exhaustion/END counting and
  ``/healthz`` staleness without the loss accounting a real death pays.
* **Servers scale OUT and IN.** Scale-out spawns a new shard (via a
  harness-registered spawner, the ops plane's ``POST /fleet/scale``, or
  automatically under the PR 5/8 memory watermarks before spill or
  backpressure engage), attaches it through the same allocation dance,
  and bootstraps it from a DONOR: the master picks the most-loaded live
  server and directs it to rebalance — the donor ships a slice of its
  unpinned untargeted backlog through the acked migration plane (the
  same serialized-unit wire format the WAL/checkpoint shard family
  uses), so every put acked before the scale-out stays fetchable after
  it and a destination death mid-ship hands the units back. Scale-in
  drains a server through the failover promote path WITHOUT counting
  losses: the draining server force-bootstraps a full-state replication
  stream to its ring buddy, flushes it, announces ``drain_done``, and
  exits; the buddy promotes a complete mirror (``failover_lost`` 0 by
  construction) and clients remap via the epoch-stamped
  ``TA_HOME_TAKEOVER`` plane PR 4 built.
* **Exhaustion/END counting is epoch-based.** Every membership change
  (attach, detach, server join, drain, failover death) bumps one fleet
  epoch; exhaustion and END ring tokens are stamped with it and a token
  crossing an epoch boundary is voided, so a rank joining mid-ring can
  never race a termination verdict. Attach is refused outright once
  termination is underway.

The wire surface is three epoch-stamped tags (``FA_MEMBER`` /
``TA_MEMBER_RESP`` / ``SS_MEMBER``, appended to the codec registry) plus
the ``/fleet`` ops routes; python servers only — native daemons keep the
reference's fixed-world model and an attach toward them is refused
loudly.

:class:`MemberView` is the dynamic world view every server (and every
attached client) holds: it duck-types :class:`WorldSpec`, delegating to
the immutable base spec until membership actually changes, so static
worlds behave identically to pre-elastic builds.
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from typing import Any, Callable, Optional, Sequence

from adlb_tpu.runtime.messages import Msg, Tag, msg
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.types import ADLB_SUCCESS, AdlbError

# Provisional rank ids for the attach negotiation: a joiner needs a
# source id before the master has allocated its real one. Ids live far
# above any real rank (and any sidecar pseudo-rank), so every server
# classifies them as neither app nor server; the endpoint is re-keyed to
# the allocated rank the moment TA_MEMBER_RESP arrives.
PROV_BASE = 1 << 30
_prov_counter = itertools.count()


def provisional_rank() -> int:
    """A process-unique provisional id (pid + counter + entropy keep
    concurrent joiners from distinct processes apart)."""
    return (
        PROV_BASE
        + ((os.getpid() & 0xFFF) << 16)
        + ((next(_prov_counter) & 0xFF) << 8)
        + random.getrandbits(8)
    )


def is_provisional(rank: int) -> bool:
    return rank >= PROV_BASE


class MemberView:
    """Dynamic world membership over an immutable :class:`WorldSpec`.

    Duck-types the spec's topology surface (``is_app``/``is_server``/
    ``home_server``/``local_apps``/``server_ranks``/``app_ranks``/
    ``ring_next``/``nservers``…) and adds mutation verbs driven by the
    SS_MEMBER plane. With no dynamic members every answer is the base
    spec's — static worlds are behavior-identical. Attribute reads not
    overridden here (``types``, ``nranks``, ``master_server_rank``,
    ``use_debug_server``, ``validate_type``…) delegate to the spec.

    ``epoch`` is THE fleet epoch: membership ops carry the master's
    allocation, failover deaths fold in via :meth:`note_epoch`, and the
    exhaustion/END tokens key on it.
    """

    def __init__(self, spec: WorldSpec) -> None:
        self.spec = spec
        self.epoch = 0
        # attached app ranks: rank -> home server (survives the home's
        # death — the takeover maps translate, exactly like base ranks)
        self.extra_apps: dict[int, int] = {}
        # scale-out servers, in join (epoch) order — ring order is the
        # base range followed by this list
        self.extra_servers: list[int] = []
        # cleanly departed app ranks (detach): no longer members, but
        # remembered so a late frame/EOF from one is ignorable
        self.detached: set[int] = set()
        # master succession (on_server_failure="failover" covering the
        # master): None means the spec's static master — the common
        # case, and the snapshot() byte-identity case. Set (with the
        # epoch of the promotion) when a deputy takes over.
        self._master_rank: Optional[int] = None
        self._master_epoch = 0

    @classmethod
    def of(cls, world) -> "MemberView":
        return world if isinstance(world, MemberView) else cls(world)

    # -- delegation ----------------------------------------------------------

    def __getattr__(self, name: str) -> Any:
        return getattr(self.__dict__["spec"], name)

    # -- topology (the WorldSpec surface, membership-aware) -------------------

    @property
    def nservers(self) -> int:
        return self.spec.nservers + len(self.extra_servers)

    @property
    def num_app_ranks(self) -> int:
        # the BASE app-rank count: rank-id layout math (``rank <
        # num_app_ranks``) belongs to the static spec; dynamic
        # membership questions go through is_app()/app_ranks
        return self.spec.num_app_ranks

    @property
    def server_ranks(self):
        if not self.extra_servers:
            return self.spec.server_ranks
        return list(self.spec.server_ranks) + list(self.extra_servers)

    @property
    def app_ranks(self):
        if not self.extra_apps and not self.detached:
            return self.spec.app_ranks
        base = [r for r in self.spec.app_ranks if r not in self.detached]
        return base + [r for r in self.extra_apps if r not in self.detached]

    @property
    def master_server_rank(self) -> int:
        """The CURRENT master: the spec's static choice until a master
        failover promoted a deputy (set_master). Everything that
        addresses 'the master' — job control, attach RPCs, obs gossip,
        exhaustion init — reads this dynamically."""
        if self._master_rank is not None:
            return self._master_rank
        return self.spec.master_server_rank

    def set_master(self, rank: int, epoch: int = 0) -> None:
        """Master succession (SS_MASTER_TAKEOVER): epoch-guarded, so a
        late frame from an older succession can never roll the fleet
        back to a dead brain."""
        if self._master_rank is not None and epoch < self._master_epoch:
            return
        self._master_rank = rank
        self._master_epoch = epoch
        self.note_epoch(epoch)

    def is_server(self, rank: int) -> bool:
        return self.spec.is_server(rank) or rank in self.extra_servers

    def is_app(self, rank: int) -> bool:
        if rank in self.detached:
            return False
        return self.spec.is_app(rank) or rank in self.extra_apps

    def home_server(self, app_rank: int) -> int:
        if app_rank in self.extra_apps:
            return self.extra_apps[app_rank]
        if app_rank >= self.spec.num_app_ranks:
            # an attached rank this server has not (yet) learned: the
            # caller turns this into a retriable refusal, never silent
            # misrouting through the base modulo formula
            raise KeyError(f"unknown member rank {app_rank}")
        return self.spec.home_server(app_rank)

    def local_apps(self, server_rank: int) -> list[int]:
        base = [
            r for r in self.spec.local_apps(server_rank)
            if r not in self.detached
        ]
        base += [
            r for r, h in self.extra_apps.items()
            if h == server_rank and r not in self.detached
        ]
        return base

    def ring_next(self, server_rank: int) -> int:
        """Ring successor over the DYNAMIC server list (base order, then
        scale-out servers in join order — identical on every server
        because joins are epoch-ordered by the master's fan-out)."""
        if not self.extra_servers:
            return self.spec.ring_next(server_rank)
        ring = list(self.spec.server_ranks) + list(self.extra_servers)
        try:
            i = ring.index(server_rank)
        except ValueError:
            return self.spec.ring_next(server_rank)
        return ring[(i + 1) % len(ring)]

    # -- mutation (the SS_MEMBER plane) ---------------------------------------

    def note_epoch(self, epoch: int) -> None:
        if epoch > self.epoch:
            self.epoch = epoch

    def add_app(self, rank: int, home: int, epoch: int = 0) -> None:
        self.extra_apps[rank] = home
        self.detached.discard(rank)
        self.note_epoch(epoch)

    def remove_app(self, rank: int, epoch: int = 0) -> None:
        self.detached.add(rank)
        self.note_epoch(epoch)

    def add_server(self, rank: int, epoch: int = 0) -> None:
        if rank not in self.extra_servers and not self.spec.is_server(rank):
            self.extra_servers.append(rank)
        self.note_epoch(epoch)

    def snapshot(self) -> dict:
        """The seed a newly attached member receives in TA_MEMBER_RESP."""
        snap = {
            "epoch": self.epoch,
            "extra_apps": dict(self.extra_apps),
            "extra_servers": list(self.extra_servers),
            "detached": sorted(self.detached),
        }
        if self._master_rank is not None:
            # only after a succession: a never-failed-over world's
            # snapshot stays byte-identical to pre-succession builds
            snap["master"] = self._master_rank
            snap["master_epoch"] = self._master_epoch
        return snap

    def seed(self, snap: dict) -> None:
        self.extra_apps.update(snap.get("extra_apps") or {})
        for s in snap.get("extra_servers") or ():
            if s not in self.extra_servers and not self.spec.is_server(s):
                self.extra_servers.append(s)
        self.detached.update(snap.get("detached") or ())
        m = snap.get("master")
        if m is not None:
            self.set_master(
                int(m), int(snap.get("master_epoch", 0) or 0)
            )
        self.note_epoch(snap.get("epoch", 0) or 0)


# --------------------------------------------------------------- attach RPC


def _member_rpc(ep, master: int, fields: dict,
                timeout: float = 15.0) -> Msg:
    """Send one FA_MEMBER from a provisional endpoint and wait for the
    TA_MEMBER_RESP. Stray frames (there should be none toward a
    provisional id beyond PEER_EOF) are dropped."""
    deadline = time.monotonic() + timeout
    last_err = None
    while time.monotonic() < deadline:
        try:
            ep.send(master, msg(Tag.FA_MEMBER, ep.rank, **fields))
            break
        except OSError as e:  # master still binding (races at bring-up)
            last_err = e
            time.sleep(0.05)
    else:
        raise AdlbError(f"member rpc: master unreachable ({last_err!r})")
    while True:
        m = ep.recv(timeout=max(deadline - time.monotonic(), 0.0))
        if m is None:
            raise AdlbError("member rpc: no TA_MEMBER_RESP before timeout")
        if m.tag is Tag.TA_MEMBER_RESP:
            return m
        # anything else toward a provisional id is droppable noise


def _rekey_endpoint(ep, fabric, prov: int, rank: int) -> None:
    """Re-key a provisional endpoint to its allocated rank id."""
    ep.rank = rank
    addr_map = getattr(ep, "addr_map", None)
    if addr_map is not None and prov in addr_map:
        addr_map[rank] = addr_map.pop(prov)
    if fabric is not None:
        fabric.endpoints[rank] = ep
        fabric.endpoints.pop(prov, None)


def attach_app(
    world: WorldSpec,
    cfg: Config,
    *,
    fabric=None,
    master_addr: Optional[tuple] = None,
    self_host: str = "127.0.0.1",
    abort_event=None,
    timeout: float = 15.0,
):
    """Attach a NEW app rank to a running world and return its
    :class:`~adlb_tpu.api.AdlbContext` (wrapped in a JoinedWorld so
    ``with`` finalizes it). Exactly one of ``fabric`` (in-proc worlds)
    or ``master_addr`` (TCP worlds: the master server's (host, port))
    selects the transport. Python servers only.
    """
    from adlb_tpu.api import AdlbContext, JoinedWorld
    from adlb_tpu.runtime.client import Client

    if cfg.server_impl == "native":
        raise AdlbError(
            "elastic attach requires python servers (the native daemon "
            "keeps the reference's fixed-at-init world)"
        )
    base = world.spec if isinstance(world, MemberView) else world
    prov = provisional_rank()
    # the CURRENT master: after a master failover a MemberView resolves
    # the promoted deputy — a joiner dialing the corpse would time out
    master = world.master_server_rank
    if fabric is not None:
        ep = fabric.add_endpoint(prov)
        fields = dict(mop="attach", kind="app")
    else:
        from adlb_tpu.runtime.transport_tcp import TcpEndpoint

        if master_addr is None:
            raise ValueError("attach_app over TCP needs master_addr")
        ep = TcpEndpoint(prov, {prov: (self_host, 0), master: master_addr})
        fields = dict(mop="attach", kind="app", host=self_host,
                      port=ep.port)
    try:
        resp = _member_rpc(ep, master, fields, timeout)
    except Exception:
        close = getattr(ep, "close", None)
        if close is not None:
            close()
        if fabric is not None:
            fabric.endpoints.pop(prov, None)
        raise
    if resp.data.get("rc", -1) != ADLB_SUCCESS:
        close = getattr(ep, "close", None)
        if close is not None:
            close()
        if fabric is not None:
            fabric.endpoints.pop(prov, None)
        raise AdlbError(
            f"attach refused (rc={resp.data.get('rc')}): "
            f"{resp.data.get('error', 'world not accepting members')}"
        )
    rank = resp.rank
    _rekey_endpoint(ep, fabric, prov, rank)
    view = MemberView(base)
    view.seed(resp.data.get("member") or {})
    view.add_app(rank, resp.home, resp.data.get("epoch", 0))
    # scale-out servers' addresses (TCP): the client must be able to
    # dial them for targeted/routed traffic
    addr_map = getattr(ep, "addr_map", None)
    if addr_map is not None:
        for r, a in (resp.data.get("srv_addrs") or {}).items():
            addr_map.setdefault(int(r), tuple(a))
    client = Client(view, cfg, ep, abort_event)
    client.attached_member = True
    # takeovers/drains that PREDATE this rank: their TA_HOME_TAKEOVER
    # broadcasts can never re-arrive here, so the master seeds the
    # retired-server route map directly — round-robin/targeted traffic
    # toward a retired server resolves to the live shard owner at once
    for dead, succ in (resp.data.get("srv_route") or {}).items():
        client._srv_route.setdefault(int(dead), int(succ))
    return JoinedWorld(AdlbContext(client), ep)


def attach_server(
    world: WorldSpec,
    cfg: Config,
    *,
    fabric=None,
    master_addr: Optional[tuple] = None,
    self_host: str = "127.0.0.1",
    timeout: float = 15.0,
) -> tuple:
    """Allocate a NEW server rank from the running world's master and
    return ``(server, ep)`` — a ready-to-run
    :class:`~adlb_tpu.runtime.server.Server` whose world view is seeded
    with the fleet's current membership. The caller runs
    ``server.run()`` (thread or process); the reactor announces itself
    ready and the master directs a donor rebalance at it."""
    from adlb_tpu.runtime.server import Server

    if cfg.server_impl == "native":
        raise AdlbError("elastic scale-out requires python servers")
    base = world.spec if isinstance(world, MemberView) else world
    prov = provisional_rank()
    master = world.master_server_rank  # succession-aware (MemberView)
    if fabric is not None:
        ep = fabric.add_endpoint(prov)
        fields = dict(mop="attach", kind="server")
    else:
        from adlb_tpu.runtime.transport_tcp import TcpEndpoint

        if master_addr is None:
            raise ValueError("attach_server over TCP needs master_addr")
        ep = TcpEndpoint(prov, {prov: (self_host, 0), master: master_addr})
        fields = dict(mop="attach", kind="server", host=self_host,
                      port=ep.port)
    resp = _member_rpc(ep, master, fields, timeout)
    if resp.data.get("rc", -1) != ADLB_SUCCESS:
        close = getattr(ep, "close", None)
        if close is not None:
            close()
        if fabric is not None:
            fabric.endpoints.pop(prov, None)
        raise AdlbError(
            f"server attach refused (rc={resp.data.get('rc')}): "
            f"{resp.data.get('error', 'world not accepting members')}"
        )
    rank = resp.rank
    _rekey_endpoint(ep, fabric, prov, rank)
    view = MemberView(base)
    view.seed(resp.data.get("member") or {})
    view.add_server(rank, resp.data.get("epoch", 0))
    addr_map = getattr(ep, "addr_map", None)
    if addr_map is not None:
        for r, a in (resp.data.get("rank_addrs") or {}).items():
            addr_map.setdefault(int(r), tuple(a))
    # a scale-out shard never serves the ops endpoint (the master owns
    # it) and never opens a second flight of the same WAL generation
    import dataclasses as _dc

    scfg = _dc.replace(cfg, ops_port=None)
    server = Server(view, scfg, ep)
    # seed job-namespace lifecycle the fleet already ran (SS_JOB_CTL
    # fan-outs predate this shard)
    for jid, code, quota, name in resp.data.get("jobs") or ():
        server.jobs.restore(jid, code, quota, name)
    # servers retired BEFORE this shard existed: without these its
    # ring/buddy walks and live-member checks would include them and
    # every token forward toward one would have to fail over ad hoc
    for r in resp.data.get("srv_dead") or ():
        server._dead_servers.add(int(r))
        server._member_live.discard(int(r))
    for r in resp.data.get("srv_drained") or ():
        server._drained_servers.add(int(r))
        server._member_live.discard(int(r))
    return server, ep


# --------------------------------------------------------------- harness


class _AppThread:
    def __init__(self, rank: int, thread: threading.Thread,
                 box: dict) -> None:
        self.rank = rank
        self.thread = thread
        self.box = box

    def result(self, timeout: Optional[float] = None):
        self.thread.join(timeout)
        if self.thread.is_alive():
            raise TimeoutError(f"rank {self.rank} still running")
        if "error" in self.box:
            raise self.box["error"]
        return self.box.get("result")


class ElasticWorld:
    """In-process elastic world harness: the `run_world` plumbing opened
    up so tests (and the chaos soak's churn adversity) can attach and
    detach ranks, and scale servers out/in, WHILE the world runs.

    Servers start immediately; app ranks are launched explicitly with
    :meth:`run_app` (base ranks) / :meth:`attach_app` (dynamic ranks).
    The master's ``member_spawner`` is wired to :meth:`_spawn_server`,
    so ``POST /fleet/scale`` and the watermark autoscale path work too.
    """

    def __init__(
        self,
        num_app_ranks: int,
        nservers: int,
        types: Sequence[int],
        cfg: Optional[Config] = None,
        timeout: float = 120.0,
    ) -> None:
        from adlb_tpu.runtime.server import Server
        from adlb_tpu.runtime.transport import InProcFabric

        self.cfg = cfg or Config()
        self.world = WorldSpec(
            nranks=num_app_ranks + nservers,
            nservers=nservers,
            types=tuple(types),
        )
        self.timeout = timeout
        self.fabric = InProcFabric(self.world.nranks)
        self.servers: dict[int, Server] = {}
        self._server_threads: dict[int, threading.Thread] = {}
        self._apps: dict[int, _AppThread] = {}
        self._attached: list = []  # JoinedWorld handles from attach_app
        self._errors: list[BaseException] = []
        self._lock = threading.Lock()
        from adlb_tpu.runtime.faults import maybe_wrap

        for rank in self.world.server_ranks:
            server = Server(
                MemberView(self.world), self.cfg,
                maybe_wrap(self.fabric.endpoint(rank), self.cfg, self.world),
                self.fabric.abort_event,
            )
            self.servers[rank] = server
            t = threading.Thread(
                target=self._server_main, args=(rank, server),
                daemon=True, name=f"adlb-rank-{rank}",
            )
            self._server_threads[rank] = t
            t.start()
        self.master = self.servers[self.world.master_server_rank]
        self.master.member_spawner = self._spawn_server

    @property
    def current_master(self):
        """The server currently holding the master role. After a master
        failover the static ``self.master`` is a corpse; anything that
        polls 'the master' (scale_out readiness, ctl asks) must resolve
        the live brain instead."""
        for s in self.servers.values():
            if s.is_master and not s.done and not s.died:
                return s
        return self.master

    # -- server plumbing ------------------------------------------------------

    def _server_main(self, rank, server) -> None:
        try:
            server.run()
            if server._drained_exit:
                # TCP parity: a drained server's endpoint closes, so a
                # late frame toward it raises OSError at the sender
                # (which already counts the rank retired) instead of
                # parking silently in a dead inbox
                close = getattr(server.ep, "close", None)
                if close is not None:
                    close()
        except BaseException as e:  # noqa: BLE001 — surfaced at finish()
            with self._lock:
                self._errors.append(e)
            self.fabric.abort_event.set()

    def _spawn_server(self, alloc: dict) -> None:
        """The master's scale-out spawner: run the allocation dance off
        the reactor thread and start the new shard in this process."""
        def go():
            try:
                server, _ep = attach_server(
                    self.world, self.cfg, fabric=self.fabric
                )
            except Exception as e:  # noqa: BLE001
                with self._lock:
                    self._errors.append(e)
                return
            with self._lock:
                self.servers[server.rank] = server
            t = threading.Thread(
                target=self._server_main, args=(server.rank, server),
                daemon=True, name=f"adlb-rank-{server.rank}",
            )
            self._server_threads[server.rank] = t
            t.start()

        threading.Thread(target=go, daemon=True,
                         name="adlb-member-spawn").start()

    # -- app ranks ------------------------------------------------------------

    def _ctx_for(self, rank: int):
        from adlb_tpu.api import AdlbContext
        from adlb_tpu.runtime.client import Client
        from adlb_tpu.runtime.faults import maybe_wrap

        client = Client(
            self.world, self.cfg,
            maybe_wrap(self.fabric.endpoint(rank), self.cfg, self.world),
            self.fabric.abort_event,
        )
        return AdlbContext(client)

    def _app_main(self, ctx, fn, box: dict) -> None:
        from adlb_tpu.types import AdlbAborted

        try:
            box["result"] = fn(ctx)
        except AdlbAborted:
            box["aborted"] = True
        except BaseException as e:  # noqa: BLE001
            box["error"] = e
            self.fabric.abort_event.set()
        finally:
            try:
                ctx._c.finalize()
            except Exception:  # teardown races are benign
                pass

    def run_app(self, rank: int, fn: Callable) -> _AppThread:
        """Launch a BASE app rank's body on its own thread."""
        box: dict = {}
        ctx = self._ctx_for(rank)
        t = threading.Thread(target=self._app_main, args=(ctx, fn, box),
                             daemon=True, name=f"adlb-rank-{rank}")
        handle = _AppThread(rank, t, box)
        self._apps[rank] = handle
        t.start()
        return handle

    def attach_ctx(self):
        """Attach a new dynamic rank; returns the JoinedWorld handle
        (use as a context manager, or call .ctx / detach explicitly)."""
        # dial through the live brain's MemberView: after a master
        # failover the static spec names a corpse
        jw = attach_app(self.current_master.world, self.cfg,
                        fabric=self.fabric,
                        abort_event=self.fabric.abort_event)
        self._attached.append(jw)
        return jw

    def attach_app(self, fn: Callable) -> _AppThread:
        """Attach a new rank and run ``fn(ctx)`` on a thread; the rank
        finalizes (stays a member, counted by the END ring) on return."""
        jw = self.attach_ctx()
        box: dict = {}
        t = threading.Thread(
            target=self._app_main, args=(jw.ctx, fn, box),
            daemon=True, name=f"adlb-rank-{jw.ctx.rank}",
        )
        handle = _AppThread(jw.ctx.rank, t, box)
        self._apps[jw.ctx.rank] = handle
        t.start()
        return handle

    # -- scale ----------------------------------------------------------------

    def scale_out(self, timeout: float = 30.0) -> int:
        """Spawn + attach + bootstrap one new server shard; returns its
        rank once the master has seen it ready."""
        master = self.current_master
        before = set(master.world.extra_servers)
        master.ctl_request({"op": "scale_out"}, timeout=10.0)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            master = self.current_master
            ready = getattr(master, "_member_ready", set())
            new = [s for s in ready if s not in before]
            if new:
                return new[0]
            if self._errors:
                raise self._errors[0]
            time.sleep(0.02)
        raise TimeoutError("scale-out did not complete")

    def scale_in(self, rank: Optional[int] = None,
                 timeout: float = 30.0) -> int:
        req = {"op": "scale_in"}
        if rank is not None:
            req["rank"] = rank
        res = self.current_master.ctl_request(req, timeout=10.0)
        drained = res["rank"]
        t = self._server_threads.get(drained)
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise TimeoutError(f"server {drained} did not drain")
        return drained

    # -- teardown -------------------------------------------------------------

    def finish(self, timeout: Optional[float] = None) -> dict:
        """Join every thread; returns {rank: result}. Raises the first
        captured error, mirroring run_world."""
        deadline = time.monotonic() + (timeout or self.timeout)
        results = {}
        for rank, handle in list(self._apps.items()):
            results[rank] = handle.result(
                max(deadline - time.monotonic(), 0.0)
            )
        for rank, t in list(self._server_threads.items()):
            t.join(max(deadline - time.monotonic(), 0.0))
            if t.is_alive():
                self.fabric.abort_event.set()
                raise TimeoutError(f"server {rank} did not finish")
        if self._errors:
            raise self._errors[0]
        return results

    def server_stats(self) -> dict:
        return {
            r: s.finalize_stats() for r, s in self.servers.items()
            if s.done and not s.died
        }
