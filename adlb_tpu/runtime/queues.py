"""Server-side queue structures.

Functional equivalent of the reference's ``xq`` library and its five
specialized queues (reference ``src/xq.h:91-134``, ``src/xq.c``), redesigned
around indexes instead of linear scans:

* the reference finds the highest-priority matching unit by walking a doubly
  linked list per Reserve — O(|wq| * ntypes) (reference ``src/xq.c:190-247``);
  here each (type) and (target, type) bucket is a lazy-deletion binary heap, so
  match/insert/remove are O(log n).

The semantic contract preserved from the reference:

* highest ``work_prio`` (algebraically largest) wins; FIFO among equal
  priorities (heap key includes the monotone seqno);
* work targeted at rank R is only ever handed to R, and targeted work takes
  precedence over untargeted work for its target (reference
  ``src/adlb.c:1204-1237``);
* pinned units (reserved but not yet fetched) are invisible to matching
  (reference ``src/xq.h:44-45``, ``src/xq.c:199-201``).
"""

from __future__ import annotations

import dataclasses
import heapq
import time
from typing import Iterable, Optional

from adlb_tpu.types import ADLB_LOWEST_PRIO


@dataclasses.dataclass
class WorkUnit:
    """One queued unit of work, metadata + opaque payload bytes.

    Field set mirrors the reference's ``wq_struct_t`` (reference
    ``src/xq.h:39-56``).
    """

    seqno: int
    work_type: int
    prio: int
    target_rank: int  # -1 = untargeted
    answer_rank: int
    payload: bytes
    home_server: int = -1
    common_len: int = 0
    common_server_rank: int = -1
    common_seqno: int = -1
    pinned: bool = False
    pin_rank: int = -1
    time_stamp: float = dataclasses.field(default_factory=time.monotonic)
    # failure attempts: how many times delivery of this unit failed
    # (owner-death reclaim, lease expiry, undeliverable response).
    # Survives re-enqueue, memory-pressure push, and failover replay;
    # exceeding Config(max_unit_retries) quarantines the unit instead of
    # re-enqueueing it (bounded blast radius for poison units).
    attempts: int = 0
    # job namespace (service mode): 0 = the default/legacy namespace.
    # A unit only ever matches requesters of its own job; non-default
    # jobs live in their own wq partition (PartitionedWorkQueue) with
    # per-job termination and per-tenant admission quotas.
    job: int = 0
    # disk spill tier (Config(spill_dir), runtime/spill.py): when the
    # payload has been spilled, ``payload`` is empty and ``spill_len``
    # remembers its true size; Server._unspill faults it back in before
    # any delivery/ship/snapshot path reads the bytes.
    spilled: bool = False
    spill_len: int = 0
    # unit-lifecycle trace context (Config(trace_sample), obs/journey.py):
    # 0 / None for the unsampled ~everything. A sampled unit carries the
    # client-minted trace id and its accumulated (stage, rank, t_mono)
    # span list; both travel with the unit across every path that moves
    # it (push, migrate, fused relay, replication, WAL).
    trace_id: int = 0
    spans: Optional[list] = None

    @property
    def work_len(self) -> int:
        n = self.spill_len if self.spilled else len(self.payload)
        return n + self.common_len

    @property
    def payload_len(self) -> int:
        """True payload size whether resident or spilled — metadata
        paths (balancer snapshots, push queries) must not read a
        spilled unit as empty."""
        return self.spill_len if self.spilled else len(self.payload)


class WorkQueue:
    """Indexed priority work queue (the reference's ``wq``)."""

    def __init__(self) -> None:
        self._units: dict[int, WorkUnit] = {}
        # type -> heap of (-prio, seqno) over unpinned untargeted units
        self._untargeted: dict[int, list[tuple[int, int]]] = {}
        # (target_rank, type) -> heap of (-prio, seqno) over unpinned targeted units
        self._targeted: dict[tuple[int, int], list[tuple[int, int]]] = {}
        # target_rank -> types with a (possibly stale) bucket, so any-type
        # targeted lookups touch only this rank's buckets, not every
        # (rank, type) pair ever seen; pruned as buckets drain
        self._targeted_types: dict[int, set[int]] = {}
        self.count = 0
        self.max_count = 0
        self.total_bytes = 0
        # O(1) availability signal for the balancer's snapshot gating:
        # number of unpinned untargeted units (exact, unlike the lazy heaps)
        self.untargeted_avail = 0

    # -- insertion / removal -------------------------------------------------

    def add(self, unit: WorkUnit) -> None:
        assert unit.seqno not in self._units
        self._units[unit.seqno] = unit
        self.count += 1
        self.max_count = max(self.max_count, self.count)
        self.total_bytes += len(unit.payload)
        if not unit.pinned:
            self._index(unit)
            if unit.target_rank < 0:
                self.untargeted_avail += 1

    def _index(self, unit: WorkUnit) -> None:
        key = (-unit.prio, unit.seqno)
        if unit.target_rank < 0:
            heapq.heappush(self._untargeted.setdefault(unit.work_type, []), key)
        else:
            heapq.heappush(
                self._targeted.setdefault((unit.target_rank, unit.work_type), []), key
            )
            self._targeted_types.setdefault(unit.target_rank, set()).add(
                unit.work_type
            )

    def get(self, seqno: int) -> Optional[WorkUnit]:
        return self._units.get(seqno)

    def remove(self, seqno: int) -> WorkUnit:
        unit = self._units.pop(seqno)
        self.count -= 1
        self.total_bytes -= len(unit.payload)
        if not unit.pinned and unit.target_rank < 0:
            self.untargeted_avail -= 1
        return unit  # stale heap entries are skipped lazily

    # -- pin discipline ------------------------------------------------------

    def pin(self, seqno: int, rank: int) -> None:
        unit = self._units[seqno]
        if not unit.pinned and unit.target_rank < 0:
            self.untargeted_avail -= 1
        unit.pinned = True
        unit.pin_rank = rank
        # heap entry goes stale; skipped on pop

    def unpin(self, seqno: int) -> None:
        unit = self._units[seqno]
        if unit.pinned and unit.target_rank < 0:
            self.untargeted_avail += 1
        unit.pinned = False
        unit.pin_rank = -1
        self._index(unit)

    # -- matching ------------------------------------------------------------

    def _pop_best(
        self, heap: Optional[list[tuple[int, int]]], targeted_to: int
    ) -> Optional[WorkUnit]:
        """Peek the best live entry of a lazy heap, discarding stale tops."""
        if not heap:
            return None
        while heap:
            neg_prio, seqno = heap[0]
            unit = self._units.get(seqno)
            if (
                unit is None
                or unit.pinned
                or unit.prio != -neg_prio
                or (targeted_to >= 0 and unit.target_rank != targeted_to)
                or (targeted_to < 0 and unit.target_rank >= 0)
            ):
                heapq.heappop(heap)  # stale
                continue
            return unit
        return None

    def _best_of(
        self, heaps: Iterable[tuple[Optional[list[tuple[int, int]]], int]]
    ) -> Optional[WorkUnit]:
        best: Optional[WorkUnit] = None
        for heap, targeted_to in heaps:
            unit = self._pop_best(heap, targeted_to)
            if unit is not None and (
                best is None
                or unit.prio > best.prio
                or (unit.prio == best.prio and unit.seqno < best.seqno)
            ):
                best = unit
        return best

    def find_targeted(self, rank: int, req_types: Optional[frozenset[int]]) -> Optional[WorkUnit]:
        """Best unpinned unit targeted at `rank` with a requested type.

        req_types None means "any type" (reference ADLB_RESERVE_REQUEST_ANY).
        """
        types = self._targeted_types.get(rank)
        if not types:
            return None
        cand = types if req_types is None else types & req_types
        best: Optional[WorkUnit] = None
        for t in list(cand):
            heap = self._targeted.get((rank, t))
            unit = self._pop_best(heap, rank)
            if unit is None:
                if not heap:  # fully drained: prune (unpin re-indexes)
                    self._targeted.pop((rank, t), None)
                    types.discard(t)
                continue
            if best is None or unit.prio > best.prio or (
                unit.prio == best.prio and unit.seqno < best.seqno
            ):
                best = unit
        if not types:
            del self._targeted_types[rank]
        return best

    def find_untargeted(self, req_types: Optional[frozenset[int]]) -> Optional[WorkUnit]:
        """Best unpinned untargeted unit of a requested type."""
        if req_types is None:
            types: Iterable[int] = list(self._untargeted.keys())
        else:
            types = req_types
        return self._best_of((self._untargeted.get(t), -1) for t in types)

    def find_match(self, rank: int, req_types: Optional[frozenset[int]]) -> Optional[WorkUnit]:
        """Reference match order: work targeted at the requester first, then
        best untargeted by priority (reference ``src/adlb.c:1204-1237``)."""
        unit = self.find_targeted(rank, req_types)
        if unit is not None:
            return unit
        return self.find_untargeted(req_types)

    def find_unpinned(self) -> Optional[WorkUnit]:
        """Any unpinned unit — used by the memory-pressure push path
        (reference ``src/xq.c:266-281``). Prefers untargeted (moving targeted
        work requires directory fixups), lowest priority first so urgent work
        stays local."""
        worst: Optional[WorkUnit] = None
        for unit in self._units.values():
            if unit.pinned:
                continue
            if unit.target_rank < 0 and (worst is None or unit.prio < worst.prio):
                worst = unit
        if worst is not None:
            return worst
        for unit in self._units.values():
            if not unit.pinned:
                return unit
        return None

    # -- stats for gossip / balancer -----------------------------------------

    def num_unpinned(self) -> int:
        """All unpinned units. The exhaustion vote compares this against
        ``count``: a difference means pinned units, i.e. handoffs still in
        flight, and the server cannot vote 'exhausted'."""
        return sum(1 for u in self._units.values() if not u.pinned)

    def num_unpinned_untargeted(self) -> int:
        return sum(
            1 for u in self._units.values() if not u.pinned and u.target_rank < 0
        )

    def hi_prio_of_type(self, work_type: int) -> int:
        """Highest priority among available (unpinned, untargeted) units of a
        type, or ADLB_LOWEST_PRIO — one cell of the reference's qmstat vector
        (reference ``src/adlb.c:151-159``)."""
        unit = self._pop_best(self._untargeted.get(work_type), -1)
        return unit.prio if unit is not None else ADLB_LOWEST_PRIO

    def count_of_type(self, work_type: int) -> tuple[int, int]:
        """(total units of type, total bytes) — for Info_num_work_units
        (reference ``src/adlb.c:2466-2496``)."""
        n = 0
        nbytes = 0
        for u in self._units.values():
            if u.work_type == work_type:
                n += 1
                nbytes += u.work_len
        return n, nbytes

    def units(self) -> Iterable[WorkUnit]:
        return self._units.values()

    def depth_sample(self) -> tuple[int, int, int]:
        """(count, unpinned-untargeted, bytes) at O(1) — the periodic
        observability tick's queue-depth gauges (the native core has an
        identical twin, adlb_tpu/native/wq.py)."""
        return self.count, self.untargeted_avail, self.total_bytes


class PartitionedWorkQueue:
    """Per-job wq partitions behind the single-queue surface.

    Job 0 (the default/legacy namespace) keeps whatever implementation
    the config picked — including the C++ core — so single-job worlds
    run exactly the code they always did. Non-default jobs each get
    their own pure-Python :class:`WorkQueue` partition, created lazily
    on first unit and dropped when the job is killed. Seqnos stay a
    single server-wide sequence, so unit-addressed operations (get /
    pin / unpin / remove) route through a seqno->job index and every
    existing call site works unchanged; matching calls gain an optional
    ``job`` argument so a requester only ever sees its own namespace.
    """

    def __init__(self, factory) -> None:
        self._factory = factory
        self._parts: dict[int, object] = {0: factory()}
        self._job_of: dict[int, int] = {}  # seqno -> job, job != 0 only
        self._max_count = 0

    # -- partition plumbing --------------------------------------------------

    def part(self, job: int = 0):
        """The job's partition, or None when it holds nothing (job 0
        always exists)."""
        return self._parts.get(job)

    def _part_of(self, seqno: int):
        return self._parts[self._job_of.get(seqno, 0)]

    def job_ids(self) -> list[int]:
        """Non-default jobs with a (possibly empty) partition."""
        return [j for j in self._parts if j != 0]

    def has_job_units(self, min_job: int = 1) -> bool:
        """Any units queued in namespaces >= ``min_job``? The default 1
        asks about ALL non-default jobs; the tpu balancer passes its
        ``balancer_max_jobs`` so only OVERFLOW namespaces (beyond the
        planner's horizon, served by the qmstat/RFR fallback) count."""
        return any(p.count for j, p in self._parts.items() if j >= min_job)

    def drop_job(self, job: int) -> list[WorkUnit]:
        """Remove a killed job's whole partition; returns its units so
        the caller can settle memory accounting."""
        if job == 0:
            return []  # job 0 is never dropped
        part = self._parts.pop(job, None)
        if part is None:
            return []
        units = list(part.units())
        for u in units:
            self._job_of.pop(u.seqno, None)
        return units

    # -- insertion / removal / pin (seqno-routed) ----------------------------

    def add(self, unit: WorkUnit) -> None:
        job = getattr(unit, "job", 0)
        part = self._parts.get(job)
        if part is None:
            # non-default partitions are always pure-Python: the C++
            # core has no job column, and job partitions are small
            part = self._parts[job] = WorkQueue()
        if job != 0:
            self._job_of[unit.seqno] = job
        part.add(unit)
        self._max_count = max(self._max_count, self.count)

    def get(self, seqno: int) -> Optional[WorkUnit]:
        return self._part_of(seqno).get(seqno)

    def remove(self, seqno: int) -> WorkUnit:
        part = self._part_of(seqno)
        self._job_of.pop(seqno, None)
        return part.remove(seqno)

    def pin(self, seqno: int, rank: int) -> None:
        self._part_of(seqno).pin(seqno, rank)

    def unpin(self, seqno: int) -> None:
        self._part_of(seqno).unpin(seqno)

    # -- matching ------------------------------------------------------------

    def find_targeted(self, rank, req_types, job: int = 0):
        part = self._parts.get(job)
        return None if part is None else part.find_targeted(rank, req_types)

    def find_untargeted(self, req_types, job: int = 0):
        part = self._parts.get(job)
        return None if part is None else part.find_untargeted(req_types)

    def find_match(self, rank, req_types, job: int = 0):
        part = self._parts.get(job)
        return None if part is None else part.find_match(rank, req_types)

    def find_unpinned(self) -> Optional[WorkUnit]:
        # memory-pressure pushes move job-0 work only: job partitions
        # are quota-bounded at admission instead
        return self._parts[0].find_unpinned()

    # -- aggregates ----------------------------------------------------------

    @property
    def count(self) -> int:
        return sum(p.count for p in self._parts.values())

    @property
    def max_count(self) -> int:
        return max(self._max_count, self._parts[0].max_count)

    @property
    def total_bytes(self) -> int:
        return sum(p.total_bytes for p in self._parts.values())

    @property
    def untargeted_avail(self) -> int:
        return sum(p.untargeted_avail for p in self._parts.values())

    def num_unpinned(self) -> int:
        return sum(p.num_unpinned() for p in self._parts.values())

    def num_unpinned_untargeted(self) -> int:
        # qmstat's qlen cell: job-0 inventory only (job work is never
        # stolen type-blind; per-job prios ride the jq gossip table)
        return self._parts[0].num_unpinned_untargeted()

    def hi_prio_of_type(self, work_type: int, job: int = 0) -> int:
        part = self._parts.get(job)
        return ADLB_LOWEST_PRIO if part is None else part.hi_prio_of_type(
            work_type
        )

    def job_hi_prio(self) -> dict:
        """{(job, type): best prio} over non-default partitions — the
        per-job qmstat gossip cells (only nonempty types appear).
        Reads each partition's per-type untargeted index (O(jobs x
        live types) per gossip tick), not a unit scan — non-default
        partitions are always the pure-Python WorkQueue, whose lazy
        heaps hi_prio_of_type already de-stales."""
        out = {}
        for j, p in self._parts.items():
            if j == 0 or not p.count:
                continue
            for t in list(p._untargeted.keys()):
                prio = p.hi_prio_of_type(t)
                if prio > ADLB_LOWEST_PRIO:
                    out[(j, t)] = prio
        return out

    def count_of_type(self, work_type: int) -> tuple[int, int]:
        n = 0
        nbytes = 0
        for p in self._parts.values():
            pn, pb = p.count_of_type(work_type)
            n += pn
            nbytes += pb
        return n, nbytes

    def units(self) -> Iterable[WorkUnit]:
        for p in self._parts.values():
            yield from p.units()

    def depth_sample(self) -> tuple[int, int, int]:
        c, a, b = 0, 0, 0
        for p in self._parts.values():
            pc, pa, pb = p.depth_sample()
            c += pc
            a += pa
            b += pb
        return c, a, b

    def __getattr__(self, name):
        if name == "snapshot_untargeted":
            # balancer fast path: present only when the job-0 partition
            # (the native core) provides it — callers getattr-probe
            return getattr(self._parts[0], "snapshot_untargeted")
        raise AttributeError(name)


@dataclasses.dataclass
class RqEntry:
    """A parked (blocking) Reserve waiting for work (reference
    ``src/xq.h:58-64``). ``fetch`` marks a fused reserve+get (this
    framework's extension): when the match is local and prefix-free the
    payload rides the response. ``prefetch`` marks a pipelined
    ``get_work_stream`` reserve: the rank may still be computing while
    this entry is parked, so it only counts as idle for exhaustion
    voting once the client sends FA_STREAM_IDLE. ``job`` is the
    requester's attached namespace: an entry only ever matches units of
    its own job."""

    world_rank: int
    rqseqno: int
    req_types: Optional[frozenset[int]]  # None = any
    time_stamp: float = dataclasses.field(default_factory=time.monotonic)
    fetch: bool = False
    prefetch: bool = False
    job: int = 0

    def wants(self, work_type: int) -> bool:
        return self.req_types is None or work_type in self.req_types


class ReserveQueue:
    """Waiting requesters, FIFO within compatibility (the reference's ``rq``).

    Since the prefetch pipeline, one rank may park SEVERAL entries at once
    (up to its stream depth); matching stays globally FIFO across entries.
    The global order is an insertion-ordered dict keyed ``(rank, rqseqno)``
    so the per-delivery hot path (remove one entry, demote the rank's
    siblings) costs O(1)/O(depth), not a full-list scan — this runs on
    the GIL-holding reactor thread for every satisfied reserve.
    """

    def __init__(self) -> None:
        # (world_rank, rqseqno) -> entry, in global park order
        self._order: "dict[tuple[int, int], RqEntry]" = {}
        self._by_rank: dict[int, list[RqEntry]] = {}

    @staticmethod
    def _key(entry: RqEntry) -> tuple[int, int]:
        return (entry.world_rank, entry.rqseqno)

    def add(self, entry: RqEntry) -> None:
        self._order[self._key(entry)] = entry
        self._by_rank.setdefault(entry.world_rank, []).append(entry)

    def remove_entry(self, entry: RqEntry) -> Optional[RqEntry]:
        """Remove one specific parked entry (multi-entry ranks must not
        drop a sibling pipeline slot)."""
        key = self._key(entry)
        if key not in self._order:
            return None
        del self._order[key]
        own = self._by_rank.get(entry.world_rank)
        if own is not None:
            try:
                own.remove(entry)  # O(depth): pipeline lists are short
            except ValueError:
                pass
            if not own:
                del self._by_rank[entry.world_rank]
        return entry

    def remove(self, world_rank: int) -> Optional[RqEntry]:
        """Remove and return the rank's OLDEST entry (legacy single-entry
        call shape)."""
        own = self._by_rank.get(world_rank)
        if not own:
            return None
        return self.remove_entry(own[0])

    def remove_rank(self, world_rank: int) -> list[RqEntry]:
        """Remove every entry a rank holds (rank death / finalize)."""
        removed = []
        while world_rank in self._by_rank:
            removed.append(self.remove_entry(self._by_rank[world_rank][0]))
        return removed

    def remove_prefetch(self, world_rank: int) -> list[RqEntry]:
        """Remove the rank's prefetch (stream) entries only — stream
        cancel must not cancel a concurrent blocking reserve."""
        doomed = [e for e in self._by_rank.get(world_rank, ()) if e.prefetch]
        for e in doomed:
            self.remove_entry(e)
        return doomed

    def find_for_type(self, work_type: int, target_rank: int = -1,
                      job: int = 0) -> Optional[RqEntry]:
        """First waiting requester a fresh unit could satisfy (reference
        ``src/xq.c:352-444`` via ``rq_find_rank_queued_for_type``); the
        unit's job namespace must match the entry's."""
        if target_rank >= 0:
            own = self._by_rank.get(target_rank)
            if not own:
                return None
            for e in own:
                if e.job == job and e.wants(work_type):
                    return e
            return None
        for e in self._order.values():
            if e.job == job and e.wants(work_type):
                return e
        return None

    def find_entry(self, world_rank: int, rqseqno: int) -> Optional[RqEntry]:
        for e in self._by_rank.get(world_rank, ()):
            if e.rqseqno == rqseqno:
                return e
        return None

    def demote_rank(self, world_rank: int) -> None:
        """Move the rank's remaining entries to the back of the global
        park order (relative order kept). Called after delivering to the
        rank: its sibling pipeline slots are adjacent in FIFO order, and
        without the demotion a scarce trickle of units piles onto one
        streaming consumer's bank (serialized behind its compute) while
        other consumers idle. O(rank's depth): re-inserting a key moves
        it to the tail of the insertion order."""
        own = self._by_rank.get(world_rank)
        if not own or len(self._order) == len(own):
            return
        for e in own:
            key = self._key(e)
            del self._order[key]
            self._order[key] = e

    def count_for(self, world_rank: int) -> int:
        """Number of entries a rank currently has parked."""
        return len(self._by_rank.get(world_rank, ()))

    def ids_for(self, world_rank: int) -> set[int]:
        """The rank's parked rqseqnos — the idle-note reconciliation
        reads these per rank, not via a global scan."""
        return {e.rqseqno for e in self._by_rank.get(world_rank, ())}

    def has_blocking(self, world_rank: int) -> bool:
        """True when the rank holds at least one NON-prefetch entry —
        i.e. the app is synchronously blocked in reserve/get_work."""
        return any(
            not e.prefetch for e in self._by_rank.get(world_rank, ())
        )

    def waiting_ranks(self) -> list[int]:
        return list(self._by_rank)

    def oldest_age(self, now: float, stream_idle=None) -> float:
        """Age of the longest-parked requester (0 when none) — the
        observability tick's park-age gauge, the direct signal behind a
        'flat wait' shape (every tick shows someone parked this long).
        Prefetch (stream) parks of a rank NOT in ``stream_idle`` are
        excluded: the consumer is computing while its slots wait, which
        is the pipeline working as designed, not a wait."""
        ages = [
            now - e.time_stamp
            for e in self._order.values()
            if not e.prefetch
            or (stream_idle is not None and e.world_rank in stream_idle)
        ]
        return max(ages, default=0.0)

    def entries(self) -> list[RqEntry]:
        return list(self._order.values())

    def __len__(self) -> int:
        return len(self._order)

    def __contains__(self, world_rank: int) -> bool:
        return world_rank in self._by_rank


class TargetedDirectory:
    """Home server's directory of *off-home* targeted work (the reference's
    ``tq``, ``src/xq.h:73-79``): for each (app_rank, type), on which remote
    server targeted units currently sit and how many. Indexed per app rank so
    lookups touch only that rank's entries."""

    def __init__(self) -> None:
        self._d: dict[int, dict[int, dict[int, int]]] = {}  # rank -> type -> server -> n

    def add(self, app_rank: int, work_type: int, server_rank: int, n: int = 1) -> None:
        by_type = self._d.setdefault(app_rank, {})
        by_server = by_type.setdefault(work_type, {})
        by_server[server_rank] = by_server.get(server_rank, 0) + n
        if by_server[server_rank] <= 0:
            del by_server[server_rank]
            if not by_server:
                del by_type[work_type]
                if not by_type:
                    del self._d[app_rank]

    def remove(self, app_rank: int, work_type: int, server_rank: int, n: int = 1) -> None:
        self.add(app_rank, work_type, server_rank, -n)

    def lookup(
        self, app_rank: int, req_types: Optional[frozenset[int]]
    ) -> Optional[tuple[int, int]]:
        """(remote server rank, work_type) believed to hold work targeted at
        app_rank, or None."""
        by_type = self._d.get(app_rank)
        if not by_type:
            return None
        for wt, by_server in by_type.items():
            if req_types is not None and wt not in req_types:
                continue
            for server_rank in by_server:
                return server_rank, wt
        return None

    def drop_rank(self, app_rank: int) -> None:
        """Forget every directory entry for a dead target: the remote units
        themselves are dropped by their holders on SS_RANK_DEAD, so a
        surviving entry would only misdirect future RFRs."""
        self._d.pop(app_rank, None)

    def repoint(self, old_server: int, new_server: int) -> None:
        """Server failover: units believed held at ``old_server`` now live
        at its buddy (the replica replay re-enqueued them), so every
        directory count moves. Off-by-replication-lag entries are
        harmless — an RFR miss patches them like any stale belief."""
        for by_type in self._d.values():
            for by_server in by_type.values():
                n = by_server.pop(old_server, 0)
                if n:
                    by_server[new_server] = by_server.get(new_server, 0) + n


@dataclasses.dataclass
class Lease:
    """Ownership record for a reserved/pinned unit: which rank holds the
    reservation, when it was granted, and a per-server lease id (for the
    failure-timeline events). No reference analogue — upstream's pins are
    anonymous because a dead owner kills the whole job anyway; under
    ``on_worker_failure="reclaim"`` the owner matters: its death turns
    every lease it holds back into queued work."""

    seqno: int
    owner: int
    lease_id: int
    granted_at: float = dataclasses.field(default_factory=time.monotonic)
    # last explicit extension (ctx.extend_lease / FA_HEARTBEAT with a
    # seqno): the expiry scan ages a lease from max(granted_at,
    # renewed_at, owner's last-heard), so a long unit can opt out of the
    # timeout without touching the owner-wide liveness clock
    renewed_at: float = 0.0


class LeaseTable:
    """seqno -> :class:`Lease` for every currently pinned unit, with an
    owner index so reclaiming a dead rank is O(its leases), not O(wq)."""

    def __init__(self) -> None:
        self._by_seqno: dict[int, Lease] = {}
        self._by_owner: dict[int, set[int]] = {}
        self._next_id = 1

    def grant(self, seqno: int, owner: int) -> Lease:
        lease = Lease(seqno=seqno, owner=owner, lease_id=self._next_id)
        self._next_id += 1
        self._by_seqno[seqno] = lease
        self._by_owner.setdefault(owner, set()).add(seqno)
        return lease

    def renew(self, seqno: int, now: Optional[float] = None) -> bool:
        """Explicit lease extension; False when no such lease exists
        (already expired/consumed — the caller's op will be fenced or
        retried through the normal paths)."""
        lease = self._by_seqno.get(seqno)
        if lease is None:
            return False
        lease.renewed_at = time.monotonic() if now is None else now
        return True

    def leases(self) -> Iterable[Lease]:
        """Snapshot of every outstanding lease (the expiry scan mutates
        the table while iterating)."""
        return list(self._by_seqno.values())

    def oldest_age(self, now: float) -> float:
        """Age of the oldest outstanding lease (0 when none) — the
        lease_age_max_s gauge."""
        return max(
            (now - max(ls.granted_at, ls.renewed_at)
             for ls in self._by_seqno.values()),
            default=0.0,
        )

    def release(self, seqno: int) -> Optional[Lease]:
        lease = self._by_seqno.pop(seqno, None)
        if lease is not None:
            owned = self._by_owner.get(lease.owner)
            if owned is not None:
                owned.discard(seqno)
                if not owned:
                    del self._by_owner[lease.owner]
        return lease

    def owned_by(self, owner: int) -> list[Lease]:
        return [
            self._by_seqno[s] for s in sorted(self._by_owner.get(owner, ()))
        ]

    def get(self, seqno: int) -> Optional[Lease]:
        return self._by_seqno.get(seqno)

    def __len__(self) -> int:
        return len(self._by_seqno)


class CommonStore:
    """Batch-put common-prefix store (the reference's ``cq``,
    ``src/xq.h:81-88``): a shared prefix stored once, refcounted, GC'd when
    every member of the batch has been fetched (reference
    ``src/adlb.c:1135-1160``)."""

    @dataclasses.dataclass
    class Entry:
        seqno: int
        buf: bytes
        refcnt: int = -1  # -1 until End_batch_put ships the final count
        ngets: int = 0
        credits: int = 0  # extra expected gets granted before refcnt known

    def __init__(self, on_gc=None) -> None:
        self._entries: dict[int, CommonStore.Entry] = {}
        self._next_seqno = 1
        self._on_gc = on_gc  # called with the entry when its bytes are freed

    def put(self, buf: bytes) -> int:
        seqno = self._next_seqno
        self._next_seqno += 1
        self._entries[seqno] = CommonStore.Entry(seqno, buf)
        return seqno

    def entries(self) -> list["CommonStore.Entry"]:
        return list(self._entries.values())

    def restore(self, seqno: int, refcnt: int, ngets: int, buf: bytes) -> None:
        """Re-install a checkpointed entry under its original seqno (handles
        and queued units reference it by number)."""
        self._entries[seqno] = CommonStore.Entry(seqno, buf, refcnt, ngets)
        self._next_seqno = max(self._next_seqno, seqno + 1)

    def adopt(self, buf: bytes, refcnt: int, ngets: int,
              credits: int = 0) -> int:
        """Install a prefix taken over from a dead server's replica under
        a FRESH seqno (its original seqno may collide with this store's);
        the caller records the (dead server, old seqno) -> new seqno
        translation. Returns the new seqno — possibly already GC'd when
        the replayed refcount state was already satisfied."""
        seqno = self._next_seqno
        self._next_seqno += 1
        e = CommonStore.Entry(seqno, buf, refcnt, ngets, credits)
        self._entries[seqno] = e
        self._maybe_gc(e)
        return seqno

    def set_refcnt(self, seqno: int, refcnt: int) -> None:
        e = self._entries.get(seqno)
        if e is None:
            return
        e.refcnt = refcnt + e.credits
        e.credits = 0
        self._maybe_gc(e)

    def get(self, seqno: int) -> Optional[bytes]:
        """Prefix bytes, or None when the entry is gone — callers must
        surface an error rather than KeyError the server reactor (a
        reclaim double-get race can outrun a credit; see credit())."""
        e = self._entries.get(seqno)
        if e is None:
            return None
        buf = e.buf
        e.ngets += 1
        self._maybe_gc(e)
        return buf

    def peek(self, seqno: int) -> Optional[bytes]:
        """Prefix bytes without counting a get — for re-serving a
        duplicate (re-sent) fetch that was already accounted."""
        e = self._entries.get(seqno)
        return e.buf if e is not None else None

    def credit(self, seqno: int) -> None:
        """Expect one additional get: a leased member unit was reclaimed
        from a dead owner who may already have fetched the prefix, so its
        re-consumption can fetch it a second time. Without the credit
        that second get could push ngets past refcnt early and GC the
        prefix out from under surviving members; with it, the worst case
        is a prefix that outlives its batch until world teardown (the
        dead owner never actually fetched) — a bounded leak, not a
        crash."""
        e = self._entries.get(seqno)
        if e is None:
            return  # already GC'd: the defensive get() covers the rest
        if e.refcnt >= 0:
            e.refcnt += 1
        else:
            e.credits += 1

    def forfeit(self, seqno: int) -> None:
        """Account a get that will never happen: a batch member referencing
        this prefix was dropped (targeted at a dead rank). Without this the
        refcount never reaches ngets and the prefix bytes leak for the rest
        of the run."""
        e = self._entries.get(seqno)
        if e is None:
            return  # already GC'd (every live member fetched first)
        e.ngets += 1
        self._maybe_gc(e)

    def _maybe_gc(self, e: "CommonStore.Entry") -> None:
        if e.refcnt >= 0 and e.ngets >= e.refcnt:
            del self._entries[e.seqno]
            if self._on_gc is not None:
                self._on_gc(e)

    def __len__(self) -> int:
        return len(self._entries)


class MemoryAccountant:
    """Per-server byte budget and admission control (reference
    ``src/adlb.c:3419-3474``): puts beyond the cap are rejected (the client
    retries elsewhere), and crossing ``push_threshold`` triggers
    memory-pressure pushes to less-loaded servers."""

    PUSH_FRACTION = 0.95  # reference THRESHOLD_TO_START_PUSH (src/adlb.c:93)

    def __init__(self, max_bytes: float, soft_frac: Optional[float] = None,
                 hard_frac: float = 0.0) -> None:
        self.max_bytes = max_bytes
        # soft watermark: pushes engage above it (reference semantics at
        # the default); hard watermark: 0 = backpressure off, else puts
        # above it with no eligible push destination answer ADLB_BACKOFF
        self.soft_frac = (
            self.PUSH_FRACTION if soft_frac is None else soft_frac
        )
        self.hard_frac = hard_frac
        self.curr = 0
        self.total = 0
        self.hwm = 0
        # disk spill tier: bytes whose payloads live in the spill file
        # instead of RAM. ``curr`` is RESIDENT bytes only — watermarks,
        # pushes, and admission all act on what actually occupies
        # memory; ``curr + spilled`` is the logical pool size.
        self.spilled = 0

    def try_alloc(self, nbytes: int) -> bool:
        """Admission-controlled alloc for puts (reference ``pmalloc``)."""
        if self.max_bytes > 0 and self.curr + nbytes > self.max_bytes:
            return False
        self.alloc(nbytes)
        return True

    def alloc(self, nbytes: int) -> None:
        self.curr += nbytes
        self.total += nbytes
        self.hwm = max(self.hwm, self.curr)

    def free(self, nbytes: int) -> None:
        self.curr -= nbytes

    def note_spill(self, nbytes: int) -> None:
        """Payload moved RAM -> spill file: resident shrinks, the bytes
        stay accounted to the pool."""
        self.curr -= nbytes
        self.spilled += nbytes

    def note_faultin(self, nbytes: int) -> None:
        """Payload moved spill file -> RAM."""
        self.curr += nbytes
        self.spilled -= nbytes
        self.hwm = max(self.hwm, self.curr)

    def note_spill_drop(self, nbytes: int) -> None:
        """A spilled payload was discarded outright (dead target, killed
        job) — it never returns to residency."""
        self.spilled -= nbytes

    @property
    def under_pressure(self) -> bool:
        return self.max_bytes > 0 and self.curr > self.soft_frac * self.max_bytes

    @property
    def pressure(self) -> float:
        """Fill fraction (0 when uncapped) — the mem_pressure gauge."""
        return self.curr / self.max_bytes if self.max_bytes > 0 else 0.0

    def above_hard(self, nbytes: int = 0) -> bool:
        """Would admitting nbytes cross the hard watermark? Always False
        when backpressure is off (hard_frac == 0) or uncapped."""
        return (
            self.hard_frac > 0
            and self.max_bytes > 0
            and self.curr + nbytes > self.hard_frac * self.max_bytes
        )

    def has_room(self, nbytes: int) -> bool:
        return self.max_bytes <= 0 or self.curr + nbytes <= self.soft_frac * self.max_bytes
