"""Event tracing — the rebuild's MPE-equivalent profiling layer.

The reference's wrapper layer can emit MPE state events around every API
call (``LOG_ADLB_INTERNALS``, reference ``src/adlb_prof.c:46-74``) and infer
per-work-type "user state" intervals between consecutive ``Get_reserved``
calls (``LOG_GUESS_USER_STATE``, reference ``src/adlb_prof.c:5-12,185-236``).

Here tracing is a run-time flag (``Config(trace=True)``) instead of a
compile-time one. Each rank's :class:`Tracer` records:

* one complete-span event per public API call (``adlb:put``,
  ``adlb:reserve``, ...), and
* one inferred ``user:type<T>`` span from the moment a ``get_reserved`` of
  type T returns until the rank's next API call — the app's presumed compute
  time on that unit, exactly the reference's user-state guess.

Since the observability unification, **servers trace too**: the reactor
wraps each message handler in a ``srv:<TAG>`` span and the balancer wraps
each planning round in ``balancer:round``, on a tracer whose ``pid``
marks the role. Client tracers run as ``pid=0`` ("apps"), server tracers
as ``pid=1`` ("servers"), so one merged Perfetto/chrome://tracing file
shows both sides of every reserve as two process lanes on a shared
clock (all ranks in one ``run_world`` share ``time.monotonic``).

Events use the Chrome trace-event format (``ph: "X"``, microsecond
timestamps, ``tid`` = world rank) so a merged dump loads directly in
Perfetto / chrome://tracing. :func:`merge` combines per-rank tracers;
:func:`save_chrome_trace` writes the JSON file.
"""

from __future__ import annotations

import json
import time
from contextlib import contextmanager
from typing import Iterable, Optional

PID_APP = 0
PID_SERVER = 1


def _now_us() -> float:
    return time.monotonic() * 1e6


class Tracer:
    """Per-rank event buffer. Cheap enough to leave on: one dict append per
    event, no locks on the hot path (each rank owns its tracer; the one
    cross-thread writer — the balancer thread into its server's tracer —
    rides CPython's atomic list.append). ``max_events`` bounds memory on
    long server runs; overflow increments ``dropped`` instead of growing."""

    def __init__(
        self,
        rank: int,
        pid: int = PID_APP,
        process_name: Optional[str] = None,
        max_events: int = 500_000,
    ) -> None:
        self.rank = rank
        self.pid = pid
        self.max_events = max_events
        self.dropped = 0
        self.events: list[dict] = []
        if process_name:
            # Chrome-trace metadata: names the pid lane in Perfetto
            self.events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "ts": 0.0,
                    "pid": pid,
                    "tid": 0,
                    "args": {"name": process_name},
                }
            )
        # pending user-state inference: (work_type, span start in us)
        self._user_since: Optional[tuple[int, float]] = None

    def _emit(self, ev: dict) -> None:
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        self.events.append(ev)

    @contextmanager
    def span(self, name: str, **args):
        t0 = _now_us()
        try:
            yield
        finally:
            self._emit(
                {
                    "name": name,
                    "ph": "X",
                    "ts": t0,
                    "dur": _now_us() - t0,
                    "pid": self.pid,
                    "tid": self.rank,
                    **({"args": args} if args else {}),
                }
            )

    def instant(self, name: str, **args) -> None:
        self._emit(
            {
                "name": name,
                "ph": "i",
                "ts": _now_us(),
                "s": "t",
                "pid": self.pid,
                "tid": self.rank,
                **({"args": args} if args else {}),
            }
        )

    # -- user-state inference (reference src/adlb_prof.c:185-236) -----------

    def api_entry(self) -> None:
        """Close any open inferred user-state span: the app was presumed
        computing on the last fetched unit until it came back to the API."""
        if self._user_since is None:
            return
        work_type, t0 = self._user_since
        self._user_since = None
        self._emit(
            {
                "name": f"user:type{work_type}",
                "ph": "X",
                "ts": t0,
                "dur": _now_us() - t0,
                "pid": self.pid,
                "tid": self.rank,
                "args": {"work_type": work_type},
            }
        )

    def got_work(self, work_type: int) -> None:
        """A get_reserved of `work_type` just returned — start presuming
        user compute."""
        self._user_since = (work_type, _now_us())


def merge(tracers: Iterable[Tracer]) -> list[dict]:
    events: list[dict] = []
    for t in tracers:
        events.extend(t.events)
    events.sort(key=lambda e: e["ts"])
    return events


def save_chrome_trace(events: list[dict], path: str) -> None:
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)


def span_names(events: Iterable[dict]) -> set[str]:
    return {e["name"] for e in events}
