"""Disk spill tier: memory pressure degrades to slower-fetch, not backoff.

The PR 5 watermarks turn an overfull server into backpressure
(``ADLB_BACKOFF``) or reference-style rejects — correct, but they stall
producers while *cold parked payloads* sit in RAM doing nothing.  This
module gives the server a second residency tier under
``Config(spill_dir)``: above the spill watermark (default: the soft
watermark), the server moves the largest/coldest unpinned payloads to an
append-only per-server file and keeps only the unit metadata resident;
delivery faults the bytes back in transparently (``Server._unspill`` at
pin/push/migrate/checkpoint/quarantine time).  ``MemoryAccountant``
tracks resident and spilled bytes separately, so admission control sees
only what actually occupies RAM.

On-disk format reuses the WAL's crc-framed records (``<II`` crc32 +
length, wal.py) over a tiny ``<qI`` (seqno, payload length) header —
a torn or corrupt record is detected at fault-in and surfaces as a
loud error, never silently different bytes.  The file is *residency
management*, not durability: it is truncated at server start (a dead
server's pool recovers via the WAL/replica paths, which always carry
full payloads), and space from faulted-in records is reclaimed by
rewriting live records once dead bytes dominate.
"""

from __future__ import annotations

import os
import struct
import zlib

# record framing shared with the WAL: crc32 of the body, body length
_REC = struct.Struct("<II")
# body header: unit seqno, payload length
_SPILLHDR = struct.Struct("<qI")

# compaction trigger: dead (faulted-in / discarded) bytes must both
# exceed this floor and outweigh the live remainder 2:1
COMPACT_MIN_DEAD = 4 << 20


class SpillCorruption(RuntimeError):
    """A spill record failed its CRC/length check at fault-in."""


class SpillStore:
    """Append-only payload spill file with an in-memory index."""

    def __init__(self, spill_dir: str, rank: int) -> None:
        os.makedirs(spill_dir, exist_ok=True)
        self.path = os.path.join(spill_dir, f"spill.{rank}.dat")
        # a previous incarnation's file indexes nothing we know: truncate
        self._f = open(self.path, "w+b")
        self._index: dict[int, tuple[int, int]] = {}  # seqno -> (off, n)
        self.live_bytes = 0
        self.dead_bytes = 0
        self.spills = 0
        self.faultins = 0
        self.compactions = 0

    def __len__(self) -> int:
        return len(self._index)

    def __contains__(self, seqno: int) -> bool:
        return seqno in self._index

    def put(self, seqno: int, payload: bytes) -> None:
        assert seqno not in self._index
        body_hdr = _SPILLHDR.pack(seqno, len(payload))
        crc = zlib.crc32(payload, zlib.crc32(body_hdr))
        f = self._f
        f.seek(0, os.SEEK_END)
        off = f.tell()
        f.write(_REC.pack(crc, _SPILLHDR.size + len(payload)))
        f.write(body_hdr)
        f.write(payload)
        # flush to the page cache (no fsync — this is residency, not
        # durability): bytes held in the interpreter's file buffer
        # would defeat the memory relief being bought
        f.flush()
        self._index[seqno] = (off, len(payload))
        self.live_bytes += len(payload)
        self.spills += 1

    def take(self, seqno: int) -> bytes:
        """Fault one payload back in (removes it from the store)."""
        off, n = self._index.pop(seqno)
        f = self._f
        f.seek(off)
        rec = f.read(_REC.size + _SPILLHDR.size + n)
        if len(rec) < _REC.size + _SPILLHDR.size + n:
            raise SpillCorruption(
                f"spill record for seqno {seqno} truncated ({self.path})"
            )
        crc, ln = _REC.unpack_from(rec, 0)
        body = rec[_REC.size:]
        if ln != len(body) or zlib.crc32(body) != crc:
            raise SpillCorruption(
                f"spill record for seqno {seqno} failed CRC ({self.path})"
            )
        sq, pn = _SPILLHDR.unpack_from(body, 0)
        if sq != seqno or pn != n:
            raise SpillCorruption(
                f"spill record at {off} names seqno {sq}, wanted {seqno}"
            )
        self.live_bytes -= n
        self.dead_bytes += n
        self.faultins += 1
        self._maybe_compact()
        return body[_SPILLHDR.size:]

    def discard(self, seqno: int) -> int:
        """Drop a spilled payload that will never be delivered (dead
        targeted rank, killed job); returns the bytes released."""
        entry = self._index.pop(seqno, None)
        if entry is None:
            return 0
        _, n = entry
        self.live_bytes -= n
        self.dead_bytes += n
        self._maybe_compact()
        return n

    # -- space reclamation ---------------------------------------------------

    def _maybe_compact(self) -> None:
        if (self.dead_bytes >= COMPACT_MIN_DEAD
                and self.dead_bytes > 2 * max(self.live_bytes, 1)):
            self.compact()

    def compact(self) -> None:
        """Rewrite live records into a fresh file (atomic swap)."""
        newpath = self.path + ".new"
        new_index: dict[int, tuple[int, int]] = {}
        with open(newpath, "w+b") as nf:
            for seqno, (off, n) in self._index.items():
                self._f.seek(off)
                rec = self._f.read(_REC.size + _SPILLHDR.size + n)
                new_index[seqno] = (nf.tell(), n)
                nf.write(rec)
        os.replace(newpath, self.path)
        self._f.close()
        self._f = open(self.path, "r+b")
        self._index = new_index
        self.dead_bytes = 0
        self.compactions += 1

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass
