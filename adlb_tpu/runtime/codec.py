"""Binary wire codec for the client<->server protocol.

Python ranks exchange pickled `Msg` frames; native (C/C++/Fortran) clients
speak this compact TLV codec instead — the moral equivalent of the
reference's fixed-layout int-vector headers (``IBUF_NUMINTS``, reference
``src/adlb.c:89-91``), but self-describing so the protocol can grow.

Frame body layout (after the transport's u32 length prefix):

    u8  magic      0x01  (pickle bodies start with 0x80 — the PROTO opcode —
                          so the first byte discriminates the codec)
    u16 tag        wire id (reference-style numbering, src/adlb.c:44-83)
    i32 src        sender world rank
    u16 nfields
    then per field:
      u8 field_id
      u8 kind      0 = i64, 1 = bytes (u32 len + data), 2 = i64 list
                   (u16 count + i64s), 3 = f64
      ...value...

All integers little-endian. A field absent from the frame is absent from
``Msg.data`` (the Python side treats missing ``req_types`` as "any type",
matching the reference's ADLB_RESERVE_REQUEST_ANY).

The C twin of this file is ``adlb_tpu/native/libadlb.cpp``; keep the tables
in sync.
"""

from __future__ import annotations

import io
import os
import pickle
import struct

from adlb_tpu.runtime.messages import Msg, Tag

BINARY_MAGIC = 0x01
PICKLE_MAGIC = 0x80  # pickle protocol >= 2 PROTO opcode

# Globals the transport's unpickler will resolve. Plain data (dict, list,
# str, bytes, int, ...) needs no globals at all; what DOES is the Msg
# envelope itself, its Tag enum, and a few container builtins. Everything
# else — os.system, subprocess.*, arbitrary constructors — is refused, so
# a stray or hostile connection cannot turn the Python transport's pickle
# path into code execution (the C planes got the matching frame-decoder
# hardening; this is the Python plane's half).
_SAFE_PICKLE_GLOBALS: set[tuple[str, str]] = {
    ("adlb_tpu.runtime.messages", "Msg"),
    ("adlb_tpu.runtime.messages", "Tag"),
    ("builtins", "complex"),
    ("builtins", "bytearray"),
    ("builtins", "set"),
    ("builtins", "frozenset"),
}


def register_safe_pickle(module: str, *names: str) -> None:
    """Allow app-message payloads to carry instances of the named classes.

    App-to-app messages (``ctx.app_send``) may hold arbitrary picklable
    Python objects between Python ranks; custom classes must be declared
    here (on the RECEIVING process, before the world starts) or the
    transport refuses the frame."""
    for n in names:
        _SAFE_PICKLE_GLOBALS.add((module, n))


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _SAFE_PICKLE_GLOBALS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"pickle global {module}.{name} is not a protocol type; if an "
            f"app message legitimately carries it, declare it with "
            f"adlb_tpu.runtime.codec.register_safe_pickle({module!r}, "
            f"{name!r}) on the receiving rank"
        )


def loads_restricted(body: bytes):
    """Unpickle a transport frame, refusing non-protocol globals."""
    return _RestrictedUnpickler(io.BytesIO(body)).load()

# Wire ids: client-facing tags keep the reference's numbers where one exists
# (reference src/adlb.c:44-83); the rest are assigned in the 11xx block.
WIRE_TAG: dict[Tag, int] = {
    Tag.FA_PUT: 1001,
    Tag.FA_PUT_COMMON: 1003,
    Tag.FA_BATCH_DONE: 1005,
    Tag.FA_DID_PUT_AT_REMOTE: 1006,
    Tag.FA_RESERVE: 1007,
    Tag.FA_GET_RESERVED: 1009,
    Tag.FA_NO_MORE_WORK: 1011,
    Tag.FA_LOCAL_APP_DONE: 1012,
    Tag.FA_ABORT: 1027,
    Tag.FA_INFO_NUM_WORK_UNITS: 1037,
    Tag.FA_GET_COMMON: 1038,
    Tag.FA_INFO_GET: 1041,
    Tag.TA_RESERVE_RESP: 1008,
    Tag.TA_GET_RESERVED_RESP: 1010,
    Tag.TA_PUT_RESP: 1020,
    Tag.TA_GET_COMMON_RESP: 1039,
    Tag.TA_PUT_COMMON_RESP: 1042,
    Tag.TA_INFO_NUM_RESP: 1043,
    Tag.TA_INFO_GET_RESP: 1044,
    Tag.TA_ABORT: 1046,
    # checkpoint/resume (Python-server feature; pickle-only frames — the
    # client refuses it toward native servers)
    Tag.FA_CHECKPOINT: 1048,
    Tag.TA_CHECKPOINT_RESP: 1049,
    # prefetch pipeline (get_work_stream; Python servers only — native
    # daemons reject tags outside their known ranges, so the client
    # degrades the stream to repeated fused get_work toward them)
    Tag.FA_STREAM_IDLE: 1051,
    Tag.FA_STREAM_CANCEL: 1052,
    Tag.TA_STREAM_CANCEL_RESP: 1053,
    # gray-failure surface (Config(lease_timeout_s) / max_unit_retries;
    # Python servers only — the policy is rejected toward native planes,
    # and native daemons parse-and-ignore FA_HEARTBEAT): liveness beacon /
    # lease extension, and the dead-letter retrieval round trip
    Tag.FA_HEARTBEAT: 1054,
    Tag.FA_GET_QUARANTINED: 1055,
    Tag.TA_QUARANTINED_RESP: 1056,
    # job control plane (service mode; Python servers only — the
    # /jobs surface and per-job termination live in the Python reactor.
    # Ids reserved so a native plane can join the protocol; native
    # daemons reject tags outside their known ranges today.)
    Tag.FA_JOB_CTL: 1057,
    Tag.TA_JOB_CTL_RESP: 1058,
    # elastic membership (adlb_tpu/runtime/membership.py; python-only —
    # native daemons keep the fixed-at-init world and reject these tags,
    # which is the loud mixed-version degradation we want)
    Tag.FA_MEMBER: 1059,
    Tag.TA_MEMBER_RESP: 1060,
    # app<->app point-to-point (the reference's app_comm traffic; native
    # clients receive it via ADLB_App_recv — bytes payloads only, enforced
    # by encodable())
    Tag.AM_APP: 1047,
    # server<->server + balancer + debug tags (Python<->Python, normally
    # pickled; ids exist so the codec is total)
    Tag.SS_QMSTAT: 1101,
    Tag.SS_RFR: 1102,
    Tag.SS_RFR_RESP: 1103,
    Tag.SS_UNRESERVE: 1104,
    Tag.SS_PUSH_QUERY: 1105,
    Tag.SS_PUSH_QUERY_RESP: 1106,
    Tag.SS_PUSH_WORK: 1107,
    Tag.SS_PUSH_DEL: 1108,
    Tag.SS_MOVING_TARGETED_WORK: 1109,
    Tag.SS_NO_MORE_WORK: 1110,
    Tag.SS_EXHAUST_CHK_1: 1111,
    Tag.SS_EXHAUST_CHK_2: 1112,
    Tag.SS_DONE_BY_EXHAUSTION: 1113,
    Tag.SS_END_1: 1114,
    Tag.SS_END_2: 1115,
    Tag.SS_ABORT: 1116,
    Tag.SS_STATE: 1117,
    Tag.SS_STATE_DELTA: 1125,
    Tag.SS_HUNGRY: 1124,
    Tag.SS_PLAN_MATCH: 1118,
    Tag.SS_PLAN_MIGRATE: 1119,
    Tag.SS_MIGRATE_WORK: 1120,
    Tag.SS_MIGRATE_ACK: 1121,
    Tag.SS_PERIODIC_STATS: 1122,
    Tag.SS_CHECKPOINT: 1123,
    Tag.DS_LOG: 1131,
    Tag.DS_END: 1132,
    # worker-death reclaim (on_worker_failure="reclaim"; python servers
    # only today — ids reserved so a native plane can join the protocol)
    Tag.SS_RANK_DEAD: 1133,
    Tag.SS_COMMON_FORFEIT: 1134,
    # remote fused fetch delivery confirmation (home -> holder)
    Tag.SS_DELIVERED: 1135,
    # server failover (on_server_failure="failover"; python servers only —
    # the policy is rejected toward native planes, so these never cross
    # the codec; ids exist so the table stays total)
    Tag.SS_REPL: 1136,
    Tag.SS_SERVER_DEAD: 1137,
    Tag.TA_HOME_TAKEOVER: 1138,
    # job-namespace lifecycle fan-out (service mode; python-only today)
    Tag.SS_JOB_CTL: 1139,
    # fleet metrics gossip: server -> master registry-snapshot deltas +
    # closed unit journeys (python-only; pickled dict payloads)
    Tag.SS_OBS_SYNC: 1140,
    # elastic-membership fan-out/control plane (python-only; pickled —
    # the id exists so the codec table stays total and a native plane
    # could one day join the protocol)
    Tag.SS_MEMBER: 1141,
    # master failover (on_server_failure="failover"; python-only —
    # master succession fan-out from the promoted deputy, appended to
    # the registry like every wire change)
    Tag.SS_MASTER_TAKEOVER: 1142,
    # shm-fabric pair announcement (rides the TCP plane once per
    # connected pair; swallowed by the transport reader)
    Tag.SHM_HELLO: 1998,
    # transport-internal synthetic signal (never actually on the wire; the
    # id exists only so the codec table stays total)
    Tag.PEER_EOF: 1999,
}
TAG_FOR_WIRE = {v: k for k, v in WIRE_TAG.items()}

_KIND_I64 = 0
_KIND_BYTES = 1
_KIND_LIST = 2
_KIND_F64 = 3
_KIND_BLIST = 4  # list of byte strings: u16 count, (u32 len + bytes)*
_KIND_FLIST = 5  # list of f64: u16 count, f64*

# field name -> (wire id, kind)
FIELDS: dict[str, tuple[int, int]] = {
    "payload": (1, _KIND_BYTES),
    "work_type": (2, _KIND_I64),
    "prio": (3, _KIND_I64),
    "target_rank": (4, _KIND_I64),
    "answer_rank": (5, _KIND_I64),
    "common_len": (6, _KIND_I64),
    "common_server": (7, _KIND_I64),
    "common_seqno": (8, _KIND_I64),
    "rc": (9, _KIND_I64),
    "hint": (10, _KIND_I64),
    "req_types": (11, _KIND_LIST),
    "hang": (12, _KIND_I64),
    "rqseqno": (13, _KIND_I64),
    "handle": (14, _KIND_LIST),
    "work_len": (15, _KIND_I64),
    "time_on_q": (16, _KIND_F64),
    "count": (17, _KIND_I64),
    "nbytes": (18, _KIND_I64),
    "max_wq": (19, _KIND_I64),
    "code": (20, _KIND_I64),
    "seqno": (21, _KIND_I64),
    "refcnt": (22, _KIND_I64),
    "server_rank": (23, _KIND_I64),
    "key": (24, _KIND_I64),
    "value": (25, _KIND_F64),
    "apptag": (26, _KIND_I64),
    # balancer sidecar <-> native server (ids 27..45 are native-server-only,
    # defined in serverd.cpp; these cross the Python boundary because the
    # sidecar is the Python/JAX balancer brain driving native servers)
    "for_rank": (29, _KIND_I64),
    "req_home": (46, _KIND_I64),
    "dest": (47, _KIND_I64),
    "seqnos": (48, _KIND_LIST),
    "tasks_flat": (49, _KIND_LIST),
    "reqs_flat": (50, _KIND_LIST),
    "consumers": (51, _KIND_I64),
    # native server -> Python debug server heartbeats (DS_LOG)
    "wq_count": (54, _KIND_I64),
    "rq_count": (55, _KIND_I64),
    # pipelined puts: client-chosen id echoed in TA_PUT_RESP so responses
    # can arrive out of band (iput/flush_puts)
    "put_id": (58, _KIND_I64),
    # fused reserve+get (get_work): payload rides TA_RESERVE_RESP when the
    # unit is local and prefix-free
    "fetch": (59, _KIND_I64),
    # balancer -> servers: parked requesters exist somewhere, so put-side
    # event snapshots are worth sending (SS_HUNGRY; req_types carries the
    # wanted-type set, omitted = an any-type requester is parked)
    "hungry": (60, _KIND_I64),
    "grew": (61, _KIND_I64),
    # 62 = exhaustion token id (native server<->server only; reserved here)
    # extended DS_LOG heartbeat (the reference's 11 counters,
    # src/adlb.c:3222-3259): native daemons -> Python debug server
    "events": (63, _KIND_I64),
    "wq_targeted": (64, _KIND_I64),
    "reserves": (65, _KIND_I64),
    "reserves_immed": (66, _KIND_I64),
    "reserves_parked": (67, _KIND_I64),
    "rfr_failed": (68, _KIND_I64),
    "ss_msgs": (69, _KIND_I64),
    "backlog": (70, _KIND_I64),
    "rss_kb": (71, _KIND_I64),
    # checkpoint/resume toward native servers (FA_CHECKPOINT carries the
    # shard path prefix as bytes; the SS ring token's per-rank counts ride
    # parallel lists — the Python plane's pickled dict token never crosses
    # this codec)
    "path": (72, _KIND_BYTES),
    "client": (73, _KIND_I64),
    "started": (74, _KIND_I64),
    "ck_counts": (76, _KIND_LIST),
    # migration-batch acknowledgment: the planner stamps each
    # SS_PLAN_MIGRATE with a batch id (mig_id, forwarded in
    # SS_MIGRATE_WORK); destinations report, per SOURCE server, the
    # highest id received (mig_acks: flattened (src, id) pairs) so
    # in-flight credits clear exactly when the batch becomes visible in
    # inventory — per source because transport ordering only holds per
    # sender pair
    "mig_id": (77, _KIND_I64),
    "mig_acks": (78, _KIND_LIST),
    # batched fused fetch (get_work_batch): how many local prefix-free
    # units one TA_RESERVE_RESP may carry, plus the batch RESPONSE's
    # parallel per-unit fields — payloads with the per-unit metadata in
    # matching order. A server that predates the request field ignores
    # it and answers single-unit fused; the client handles either shape.
    "fetch_max": (79, _KIND_I64),
    "payloads": (80, _KIND_BLIST),
    "work_types": (81, _KIND_LIST),
    "prios": (82, _KIND_LIST),
    "answer_ranks": (83, _KIND_LIST),
    "times_on_q": (84, _KIND_FLIST),
    # batched SS_STATE_DELTA (round 4): puts arriving faster than
    # balancer_min_gap accumulate and flush as ONE delta with parallel
    # per-unit lists (seqnos/work_types/prios/work_lens), so the
    # balancer's inventory view tracks a streaming producer within one
    # gap instead of one unit per gap
    "work_lens": (85, _KIND_LIST),
    # worker-death reclaim: the dead world rank (SS_RANK_DEAD) and the
    # batch-common fixup op (SS_COMMON_FORFEIT; "forfeit" | "credit",
    # as bytes over the wire like "path")
    "rank": (86, _KIND_I64),
    "op": (87, _KIND_BYTES),
    # per-client FA_GET_COMMON request id: consecutive fetches of the
    # SAME prefix are legitimate (one per batch member), so duplicate
    # re-sends can only be told apart by id (native daemons parse-and-
    # ignore unknown ids, so this is plane-compatible)
    "get_id": (88, _KIND_I64),
    # prefetch pipeline: FA_RESERVE sent by a get_work_stream slot — the
    # rank may still be computing, so the park only counts as idle for
    # exhaustion voting after FA_STREAM_IDLE (native daemons parse-and-
    # ignore unknown ids)
    "prefetch": (89, _KIND_I64),
    # FA_STREAM_IDLE: the stream's in-flight reserve count — the server
    # honors the idle note only when that many entries are still parked,
    # voiding notes that crossed a delivery on the wire (legacy
    # count-only form; current clients send the slot list below)
    "inflight": (90, _KIND_I64),
    # FA_STREAM_IDLE: the outstanding reserve rqseqnos themselves — the
    # server reconciles them against its parked entries exactly (idle
    # mark on equality; swept-stream phantom slots re-armed by id)
    "slots": (91, _KIND_LIST),
    # gray-failure surface: a unit's failure-attempt count (rides
    # SS_PUSH_WORK so quarantine budgets survive memory-pressure pushes)
    # and the TA_PUT_RESP backpressure retry-after hint (ADLB_BACKOFF)
    "attempts": (92, _KIND_I64),
    "retry_after_ms": (93, _KIND_I64),
    # TA_QUARANTINED_RESP: the dead-letter store as parallel per-unit
    # lists (payloads/work_types/prios/answer_ranks/seqnos reused from
    # the batch-fetch idiom above)
    "target_ranks": (94, _KIND_LIST),
    "attempts_list": (95, _KIND_LIST),
    # ... and per-unit 0/1 flags: payload is a fused member's suffix
    # whose prefix was not stored on (or did not survive to) the
    # answering server
    "suffix_onlys": (96, _KIND_LIST),
    # job namespace (service mode): which tenant a put/reserve/ctl frame
    # belongs to. Omitted = the default namespace 0, so single-job
    # traffic is byte-identical to the pre-service protocol; native
    # daemons parse-and-ignore the field (job matching is a Python-
    # server feature today).
    "job_id": (97, _KIND_I64),
    # unit-lifecycle trace context (Config(trace_sample) head-sampling):
    # a sampled FA_PUT carries the client-minted trace id and the unit's
    # journey is recorded server-side stage by stage (obs/journey.py).
    # Omitted for unsampled puts, so trace_sample=0 worlds stay
    # byte-identical on the wire; native daemons parse-and-ignore it.
    "trace_id": (98, _KIND_I64),
    # elastic membership (FA_MEMBER/TA_MEMBER_RESP/SS_MEMBER; python-only
    # today — ids reserved append-only so a native plane joining later,
    # or a mixed-version fleet, degrades loudly instead of misparsing):
    # the fleet epoch every membership op (and exhaustion/END token)
    # keys on; the membership op name; the joiner's listener endpoint;
    # the fan-out ack token; the allocated home server; member kind
    "epoch": (99, _KIND_I64),
    "mop": (100, _KIND_BYTES),
    "host": (101, _KIND_BYTES),
    "port": (102, _KIND_I64),
    "member_tok": (103, _KIND_I64),
    "home": (104, _KIND_I64),
    "kind": (105, _KIND_BYTES),
    # multi-job planning (SS_STATE_DELTA): per-unit job ids for a
    # batched task delta whose units are not all in the default
    # namespace. Omitted when every unit is job 0, so single-job worlds
    # stay byte-identical; native daemons parse-and-ignore it (the
    # native plane advertises only the default namespace today).
    "jobs": (106, _KIND_LIST),
    # master failover (SS_MASTER_TAKEOVER, wire tag 1142): the promoted
    # deputy's rank. Rides the succession fan-out (and the extended
    # TA_HOME_TAKEOVER note) alongside the reused epoch/mop/host/port/
    # member_tok ids above. Append-only; native daemons parse-and-ignore.
    "new_master": (107, _KIND_I64),
}
FIELD_FOR_WIRE = {v[0]: (k, v[1]) for k, v in FIELDS.items()}

_HDR = struct.Struct("<BHiH")  # magic, tag, src, nfields
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")
_U16 = struct.Struct("<H")


def encodable(m: Msg) -> bool:
    """True if every field of m has a binary field id (None values are
    encoded by omission)."""
    if m.tag is Tag.AM_APP:
        # native clients receive app messages via ADLB_App_recv, but only
        # raw bytes survive the TLV form — arbitrary Python payloads would
        # silently corrupt, so they are refused with a clear error
        return isinstance(m.data.get("payload"), (bytes, bytearray))
    return all(k in FIELDS for k, v in m.data.items() if v is not None)


# bytes fields at least this large ride the iovec as zero-copy views;
# smaller ones fold into the accumulating header segment (a syscall's
# iovec slots and a ring's bookkeeping both cost more than a small copy)
IOV_INLINE_MAX = 512


def _bytes_view(value):
    """Normalize a bytes-field value to a flat byte buffer. A memoryview
    with itemsize != 1 must be cast to bytes ('B') first: ``len()`` on
    it counts ITEMS, and emitting an item count as the u32 byte length
    would desync the whole TLV stream."""
    if isinstance(value, (bytes, bytearray)):
        return value
    if isinstance(value, memoryview):
        if value.itemsize == 1 and value.ndim == 1 and value.contiguous:
            return value  # zero-copy fast path; len() == byte length
        return bytes(value)  # flatten (tobytes) — correct byte length
    return bytes(value)


def encode_binary_iov_py(m: Msg) -> list:
    """Scatter-gather form of :func:`encode_binary`: a list of buffers
    whose concatenation is the frame body, with large ``bytes`` payloads
    (put/fetch bodies, batch payload lists) left as zero-copy views
    instead of being concatenated into a fresh body. The TCP plane hands
    the list straight to ``sendmsg`` and the shm fabric writes the
    segments into the ring — either way the payload bytes are copied
    exactly once (into the kernel buffer / the ring), never first into
    an intermediate ``hdr + body`` string."""
    fields = [(k, v) for k, v in m.data.items() if v is not None]
    parts: list = []
    acc = bytearray(_HDR.pack(BINARY_MAGIC, WIRE_TAG[m.tag], m.src,
                              len(fields)))
    for name, value in fields:
        fid, kind = FIELDS[name]
        acc += struct.pack("<BB", fid, kind)
        if kind == _KIND_I64:
            acc += _I64.pack(int(value))
        elif kind == _KIND_BYTES:
            b = _bytes_view(value)
            acc += _U32.pack(len(b))
            if len(b) >= IOV_INLINE_MAX:
                parts.append(bytes(acc))
                acc = bytearray()
                parts.append(b)
            else:
                acc += b
        elif kind == _KIND_LIST:
            seq = [int(x) for x in value]
            if len(seq) > 65535:
                raise ValueError(f"list field {name} overflows u16 bound")
            acc += _U16.pack(len(seq))
            for x in seq:
                acc += _I64.pack(x)
        elif kind == _KIND_BLIST:
            if len(value) > 65535:
                raise ValueError(f"blist field {name} overflows u16 bound")
            acc += _U16.pack(len(value))
            for item in value:
                b = _bytes_view(item)
                acc += _U32.pack(len(b))
                if len(b) >= IOV_INLINE_MAX:
                    parts.append(bytes(acc))
                    acc = bytearray()
                    parts.append(b)
                else:
                    acc += b
        elif kind == _KIND_FLIST:
            seq = [float(x) for x in value]
            if len(seq) > 65535:
                raise ValueError(f"flist field {name} overflows u16 bound")
            acc += _U16.pack(len(seq))
            for x in seq:
                acc += _F64.pack(x)
        else:
            acc += _F64.pack(float(value))
    if acc:
        parts.append(bytes(acc))
    return parts


def decode_binary_py(body) -> Msg:
    magic, wire_tag, src, nfields = _HDR.unpack_from(body, 0)
    if magic != BINARY_MAGIC:
        raise ValueError(f"bad binary frame magic {magic:#x}")
    tag = TAG_FOR_WIRE[wire_tag]
    off = _HDR.size
    data: dict = {}
    for _ in range(nfields):
        fid, kind = struct.unpack_from("<BB", body, off)
        off += 2
        if kind == _KIND_I64:
            (value,) = _I64.unpack_from(body, off)
            off += 8
        elif kind == _KIND_BYTES:
            (n,) = _U32.unpack_from(body, off)
            off += 4
            if off + n > len(body):
                raise ValueError("truncated bytes field in binary frame")
            value = body[off:off + n]
            off += n
        elif kind == _KIND_LIST:
            (cnt,) = _U16.unpack_from(body, off)
            off += 2
            value = [
                _I64.unpack_from(body, off + 8 * i)[0] for i in range(cnt)
            ]
            off += 8 * cnt
        elif kind == _KIND_F64:
            (value,) = _F64.unpack_from(body, off)
            off += 8
        elif kind == _KIND_BLIST:
            (cnt,) = _U16.unpack_from(body, off)
            off += 2
            value = []
            for _i in range(cnt):
                (n,) = _U32.unpack_from(body, off)
                off += 4
                if off + n > len(body):
                    raise ValueError("truncated blist item in binary frame")
                value.append(body[off:off + n])
                off += n
        elif kind == _KIND_FLIST:
            (cnt,) = _U16.unpack_from(body, off)
            off += 2
            value = [
                _F64.unpack_from(body, off + 8 * i)[0] for i in range(cnt)
            ]
            off += 8 * cnt
        else:
            raise ValueError(f"bad field kind {kind}")
        entry = FIELD_FOR_WIRE.get(fid)
        if entry is not None:  # unknown fields are skipped, not fatal
            data[entry[0]] = value
    # protocol-level conveniences: hang arrives as 0/1
    if "hang" in data:
        data["hang"] = bool(data["hang"])
    return Msg(tag=tag, src=src, data=data)


# --------------------------------------------------------- compiled twin
#
# The hot-path encode/decode pair also exists as a C core
# (adlb_tpu/native/codec.cpp, built like wqcore by native/build.py and
# loaded through ctypes.PyDLL — the PR 7 O(1)-getter discipline: GIL
# held, PyObjects in and out, one plain C call per frame). The Python
# implementations above are retained verbatim as the fallback/reference
# twin; tests/test_codec_fuzz.py holds the two byte-identical in both
# directions. Selection is per-process at import, like wqcore:
# ``ADLB_CODEC`` env ("auto"/"c"/"py", default auto = C when the .so
# builds) decides the initial implementation, and the world harnesses
# re-apply ``Config(codec=...)`` via :func:`select_codec` ("c" there is
# strict — no silent fallback for an explicit ask).

_codec_active = "py"
_c_encode_iov = None
_c_decode = None


def _load_c_codec() -> bool:
    """Bind the compiled codec (building it if needed); False + recorded
    reason when the toolchain is unavailable."""
    global _c_encode_iov, _c_decode
    if _c_encode_iov is not None:
        return True
    from adlb_tpu.native.build import ensure_codec

    mod = ensure_codec()
    if mod is None:
        return False
    # hand the C core the live protocol tables — same objects, so the
    # twins cannot drift within a process
    mod.setup(FIELDS, IOV_INLINE_MAX, WIRE_TAG, TAG_FOR_WIRE, Msg)
    _c_encode_iov = mod.encode_iov
    _c_decode = mod.decode
    return True


_ENC_IOV = encode_binary_iov_py
_DEC = decode_binary_py


def select_codec(which: str = "auto") -> str:
    """Pick the wire-codec implementation for this process: "py" forces
    the Python twin, "c" requires the compiled core (RuntimeError when it
    cannot build), "auto" uses the compiled core when available. Returns
    the implementation now active."""
    global _ENC_IOV, _DEC, _codec_active
    if which not in ("auto", "c", "py"):
        raise ValueError(f"unknown codec {which!r}")
    if which == "py":
        _ENC_IOV, _DEC, _codec_active = encode_binary_iov_py, decode_binary_py, "py"
    elif _load_c_codec():
        _ENC_IOV, _DEC, _codec_active = _c_encode_iov, _c_decode, "c"
    elif which == "c":
        from adlb_tpu.native.build import codec_error

        raise RuntimeError(
            f"Config(codec='c') but the compiled codec is unavailable: "
            f"{codec_error()}"
        )
    else:
        _ENC_IOV, _DEC, _codec_active = encode_binary_iov_py, decode_binary_py, "py"
    return _codec_active


def active_codec() -> str:
    """Which implementation carries this process's frames ("c"/"py")."""
    return _codec_active


def encode_binary_iov(m: Msg) -> list:
    """Scatter-gather frame encode via the active implementation (see
    :func:`select_codec`); the docstring of record is on the Python twin
    :func:`encode_binary_iov_py`."""
    return _ENC_IOV(m)


def decode_binary(body) -> Msg:
    return _DEC(body)


def encode_binary(m: Msg) -> bytes:
    return b"".join(bytes(p) for p in _ENC_IOV(m))


# import-time selection, like wqcore: the env override is the CI hook
select_codec(os.environ.get("ADLB_CODEC", "auto").strip().lower() or "auto")


# ------------------------------------------------------ wire-native gate


_WIRE_NATIVE = (int, float, bytes, bytearray, memoryview)


def wire_native_ok(m: Msg) -> bool:
    """Should this python<->python frame ride the TLV body instead of
    pickle (shm rings and multiplexed TCP channels both ask)? Only
    client<->server traffic — the put/fetch hot path, whose
    TLV-into-Python-server decode is proven by the native C clients —
    and only when every value is wire-native: a str (checkpoint path,
    forfeit op) or richer object would round-trip as a different type
    than the pickle plane delivers, so those frames keep the pickle
    body."""
    name = m.tag.name
    if not (name.startswith("FA_") or name.startswith("TA_")
            or m.tag is Tag.AM_APP):
        return False
    if not encodable(m):
        return False
    for v in m.data.values():
        if v is None or isinstance(v, _WIRE_NATIVE):
            continue
        if isinstance(v, (list, tuple, frozenset, set)):
            if all(isinstance(x, _WIRE_NATIVE) for x in v):
                continue
        return False
    return True
