"""Message protocol.

Functional equivalent of the reference's 40-tag MPI wire protocol (reference
``src/adlb.c:44-83``), carried over any `Transport`. Differences from the
reference, by design:

* no rendezvous two-phase PUT (header/ack/Rsend): transports here deliver
  whole messages, and admission control happens at the receiving server,
  which replies with an accept/reject (+ least-loaded hint) like the
  reference's ACK_AND_RC (reference ``src/adlb.c:908-958``);
* the qmstat ring pass is replaced either by direct state broadcast
  (heuristic mode) or by balancer snapshot/plan messages (TPU mode).

Tag families keep the reference's naming: FA_* client->server, TA_*
server->client, SS_* server<->server, DS_* debug-server.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any


class Tag(enum.Enum):
    # client -> server
    FA_PUT = enum.auto()
    FA_PUT_COMMON = enum.auto()
    FA_BATCH_DONE = enum.auto()
    FA_DID_PUT_AT_REMOTE = enum.auto()
    FA_RESERVE = enum.auto()
    FA_GET_RESERVED = enum.auto()
    FA_GET_COMMON = enum.auto()
    FA_NO_MORE_WORK = enum.auto()
    FA_LOCAL_APP_DONE = enum.auto()
    FA_ABORT = enum.auto()
    FA_INFO_NUM_WORK_UNITS = enum.auto()
    FA_INFO_GET = enum.auto()
    # prefetch pipeline (get_work_stream; no reference analogue): the
    # client's bank ran dry and it is now genuinely blocked — its
    # prefetch-flagged reserves become park-eligible for exhaustion
    # voting (a delivery clears the mark server-side)
    FA_STREAM_IDLE = enum.auto()
    # drop this rank's prefetch reserves (stream close); acked so the
    # client can drain deliveries that raced the cancel
    FA_STREAM_CANCEL = enum.auto()
    # gray-failure detection (Config(lease_timeout_s) > 0; no reference
    # analogue): a client's liveness beacon while idle-but-computing —
    # ordinary protocol traffic already piggybacks liveness, this covers
    # the long-compute gaps. With a ``seqno`` field it doubles as an
    # explicit lease extension (ctx.extend_lease) for units whose
    # compute legitimately outlives the timeout.
    FA_HEARTBEAT = enum.auto()
    # dead-letter retrieval: list this server's quarantined units
    # (payload + metadata + attempt counts); ctx.get_quarantined()
    # aggregates the per-server responses
    FA_GET_QUARANTINED = enum.auto()
    # job control plane (service mode; no reference analogue — upstream
    # is one world = one job): submit/status/drain/kill toward the
    # MASTER (which allocates ids and fans out SS_JOB_CTL), attach
    # toward the rank's HOME server (binding the rank to a namespace
    # for per-job exhaustion voting). Same surface the ops endpoint's
    # /jobs routes expose over HTTP.
    FA_JOB_CTL = enum.auto()

    # server -> client
    TA_PUT_RESP = enum.auto()
    TA_PUT_COMMON_RESP = enum.auto()
    TA_RESERVE_RESP = enum.auto()
    TA_GET_RESERVED_RESP = enum.auto()
    TA_GET_COMMON_RESP = enum.auto()
    TA_INFO_NUM_RESP = enum.auto()
    TA_INFO_GET_RESP = enum.auto()
    TA_STREAM_CANCEL_RESP = enum.auto()
    TA_QUARANTINED_RESP = enum.auto()
    TA_JOB_CTL_RESP = enum.auto()
    TA_ABORT = enum.auto()

    # server <-> server
    SS_QMSTAT = enum.auto()
    SS_RFR = enum.auto()
    SS_RFR_RESP = enum.auto()
    SS_UNRESERVE = enum.auto()
    # remote fused fetch (no reference analogue — upstream always pays a
    # GET_RESERVED round trip to the holder, src/adlb.c:2976-3025): the
    # requester's home server confirms a payload-carrying SS_RFR_RESP
    # landed at the requester, so the holder consumes the pinned unit.
    # Until then the unit stays pinned under its lease — an UNRESERVE
    # race unpins it and a requester death reclaims it, both through the
    # existing paths.
    SS_DELIVERED = enum.auto()
    SS_PUSH_QUERY = enum.auto()
    SS_PUSH_QUERY_RESP = enum.auto()
    SS_PUSH_WORK = enum.auto()
    SS_PUSH_DEL = enum.auto()
    SS_MOVING_TARGETED_WORK = enum.auto()
    SS_NO_MORE_WORK = enum.auto()
    SS_EXHAUST_CHK_1 = enum.auto()
    SS_EXHAUST_CHK_2 = enum.auto()
    SS_DONE_BY_EXHAUSTION = enum.auto()
    SS_END_1 = enum.auto()
    SS_END_2 = enum.auto()
    SS_ABORT = enum.auto()
    SS_PERIODIC_STATS = enum.auto()  # stats ring token (src/adlb.c:2391-2465)
    # failure policy "reclaim" (no reference analogue — upstream's model is
    # rank-death-kills-job): the dead rank's home server fans this out so
    # every server reclaims leases, drops rq/targeted state, and excludes
    # the rank from termination counting
    SS_RANK_DEAD = enum.auto()
    # refcount-correct release of a batch-common prefix whose member unit
    # was dropped (targeted at a dead rank): the common server accounts a
    # forfeited get so the prefix still GCs when live members fetch
    SS_COMMON_FORFEIT = enum.auto()
    # job-namespace lifecycle fan-out (service mode): the master
    # broadcasts submit/drain/done/kill so every server's job table
    # converges; "done" additionally flushes the job's parked
    # requesters with ADLB_DONE_BY_EXHAUSTION (per-job termination)
    SS_JOB_CTL = enum.auto()
    # fleet metrics plane (no reference analogue — upstream's whole
    # diagnostic surface is end-of-run counter dumps): each non-master
    # server ships a delta-encoded registry snapshot (changed
    # counters/gauges/histograms, cumulative values) plus its closed
    # unit journeys to the master on the obs_sync_interval tick, so the
    # master's /metrics serves a merged fleet view, /healthz exposes
    # per-rank snapshot staleness, and /trace/units serves the
    # fleet-wide journey store. Armed only when ops_port is configured.
    SS_OBS_SYNC = enum.auto()

    # elastic membership (adlb_tpu/runtime/membership.py; no reference
    # analogue — upstream fixes every role at ADLB_Init):
    # FA_MEMBER — joiner (provisional id) -> MASTER: attach an app rank
    # or a scale-out server (kind="app"|"server", + listener host/port
    # on TCP fabrics); member rank -> master: clean detach. The master
    # allocates rank id + home under a fresh fleet epoch and answers
    # only after every live server acked the fan-out.
    FA_MEMBER = enum.auto()
    TA_MEMBER_RESP = enum.auto()
    # SS_MEMBER — the membership fan-out/control plane, epoch-stamped:
    # mop="attach"/"detach"/"server_join" (apply + ack toward the
    # master), "ack" (barrier), "ready" (new shard's reactor is up),
    # "rebalance" (master -> donor: ship backlog to the new shard over
    # the acked migration plane), "server_drain" (master -> all: rank S
    # is draining; S itself force-bootstraps a full replication stream
    # to its buddy, flushes, announces "drain_done", and exits — the
    # buddy promotes a COMPLETE mirror, so scale-in counts no losses)
    SS_MEMBER = enum.auto()

    # server failover (Config(on_server_failure="failover"); no reference
    # analogue — upstream's servers ARE the pool and a server death kills
    # the job, SURVEY §5):
    # SS_REPL — a server's asynchronous replication-log flush to its
    # ring-successor buddy: packed pool-mutation entries in the
    # checkpoint.py unit wire format (adlb_tpu/runtime/replica.py)
    SS_REPL = enum.auto()
    # SS_SERVER_DEAD — fan-out when a server's connection EOFs mid-run:
    # survivors prune the dead server from rings/gossip/plans, and its
    # buddy replays the replication log and takes over home-server duty
    SS_SERVER_DEAD = enum.auto()
    # TA_HOME_TAKEOVER — buddy -> app ranks: epoch-stamped remap (dead
    # server -> this server); clients reroute handles, common fetches,
    # round-robin puts, and their home-server traffic. When the dead
    # server was the MASTER the note also carries new_master (the
    # promoted deputy), so clients re-point job control and detach.
    TA_HOME_TAKEOVER = enum.auto()
    # SS_MASTER_TAKEOVER — promoted deputy -> servers: epoch-stamped
    # master succession (new_master, epoch, the rebound ops endpoint's
    # host/port) behind a member_tok ack barrier; exhaustion/END
    # verdicts defer until the barrier resolves so no termination
    # verdict races the succession. Append-only wire tag (1142).
    SS_MASTER_TAKEOVER = enum.auto()

    # balancer (TPU path; no reference analogue — replaces qmstat+RFR)
    SS_STATE = enum.auto()
    SS_STATE_DELTA = enum.auto()  # new task(s) appended to last snapshot
    # (single-unit fields, or batched parallel lists since round 4)
    SS_HUNGRY = enum.auto()  # master -> servers: parked requesters exist
    SS_PLAN_MATCH = enum.auto()
    SS_PLAN_MIGRATE = enum.auto()  # planner: move these units to dest
    SS_MIGRATE_WORK = enum.auto()  # holder -> dest: the moved units
    SS_MIGRATE_ACK = enum.auto()  # dest -> holder: units landed (or bounced)

    # checkpoint/resume (no reference analogue — the reference has no pool
    # serialization at all, SURVEY §5; this framework adds it): a client
    # asks its home server, the master circulates a ring token, every
    # server writes its shard, the origin client gets an ack with counts
    FA_CHECKPOINT = enum.auto()
    TA_CHECKPOINT_RESP = enum.auto()
    SS_CHECKPOINT = enum.auto()

    # app <-> app (the reference's app_comm: ADLB_Init hands back a
    # communicator on which app ranks exchange ordinary point-to-point
    # messages, e.g. c1.c's TAG_B_ANSWER answer flow; here the same fabric
    # carries them, tagged AM_APP with a user tag inside)
    AM_APP = enum.auto()

    # debug server
    DS_LOG = enum.auto()
    DS_END = enum.auto()

    # transport-internal, TCP-carried: a rank that just attached a
    # shared-memory ring toward the receiver announces it here (one
    # frame per pair, before any ring traffic). The TCP reader records
    # the sender and swallows the frame — roles never see it — so the
    # connection it rides becomes the pair's death sentinel: a SIGKILLed
    # shm peer EOFs this socket, and the existing PEER_EOF machinery
    # (reclaim, failover, takeover) works unchanged over the ring fabric.
    SHM_HELLO = enum.auto()

    # transport-internal (never on the wire): a peer's connection hit EOF.
    # The reference's failure model is "any rank failure kills the job"
    # (MPI_Abort paths, reference src/adlb.c:2508-2526); over TCP the
    # analogous signal is an app connection closing before LOCAL_APP_DONE.
    PEER_EOF = enum.auto()


@dataclasses.dataclass
class Msg:
    tag: Tag
    src: int
    data: dict[str, Any] = dataclasses.field(default_factory=dict)

    def __getattr__(self, name: str) -> Any:
        try:
            return self.__dict__["data"][name]
        except KeyError:
            raise AttributeError(name) from None


def msg(tag: Tag, src: int, **data: Any) -> Msg:
    return Msg(tag=tag, src=src, data=data)
