"""Shared-memory ring fabric: the third transport, for co-located ranks.

The TCP fabric pays the loopback stack (syscalls, softirq, per-frame
wakeups) even when both ranks sit on one host — bench r05/r06 put the
intra-host per-op floor at ~0.66-0.9 ms p50, transport-bound (ROADMAP
item 4).  This module moves the same-host data plane into user space:

* one **SPSC byte ring** per direction per connected pair, living in a
  named shared-memory segment (a ``/dev/shm``-backed ``mmap`` — see
  :class:`ShmRing` for why not ``multiprocessing.shared_memory``); the
  sender creates the ring it writes, the receiver attaches on
  announcement and unlinks at close (the world sweep catches strays);
* **seqlock-style head/tail**: two monotone u64 cursors, each written
  by exactly one side.  A stale cursor read is always *conservative*
  (the reader sees less available, the writer sees less space), so the
  discipline needs no locks — only that the data copy lands before the
  cursor bump, which x86-64's total store order gives the interpreter's
  separate stores;
* a **named-FIFO doorbell** per rank for blocking recv: senders write
  one byte after ring writes, the receiver ``select``\\ s on its FIFO —
  the portable stand-in for a futex/eventfd wakeup that still works
  across ``exec``\\ ed processes (launch.py worlds), where an inherited
  eventfd cannot reach;
* frames bigger than the ring **stream through it**: the writer copies
  what fits, rings the bell, and continues as the reader frees space —
  a >1 MiB payload needs no oversized ring, just one extra wakeup per
  ring-full of bytes.

The fabric is a *wrapper* over :class:`TcpEndpoint`, not a replacement:
the first send toward each peer probes for the peer's doorbell FIFO
(same host + fabric enabled ⇒ it exists), upgrades the pair to a ring
and announces it with one ``SHM_HELLO`` frame over TCP — cross-host
peers, native daemons, and plain-TCP peers silently stay on TCP.  The
HELLO's connection doubles as the pair's **death sentinel**: a
SIGKILLed shm peer EOFs it, the TCP reader synthesizes ``PEER_EOF``,
and every failure-policy ladder (reclaim, failover, lease fencing)
works over the ring fabric unchanged.  ``FaultyEndpoint`` stacks on
top exactly as it does over TCP.

Bodies use the same first-byte discrimination as the TCP plane: frames
whose fields all have TLV ids are written as scatter-gather TLV
segments (``codec.encode_binary_iov`` — header + fields + payload
views straight into the ring, no body-concat copy); everything else is
a restricted-unpickle pickle body.
"""

from __future__ import annotations

import glob
import mmap
import os
import pickle
import queue
import select
import struct
import threading
import time
import uuid
from typing import Optional

from adlb_tpu.runtime.codec import (
    decode_binary,
    encode_binary_iov,
    loads_restricted,
    wire_native_ok,
)
from adlb_tpu.runtime.messages import Msg, Tag, msg

_LEN = struct.Struct("<I")   # per-frame body length prefix inside the ring
_CUR = struct.Struct("<Q")   # head/tail cursors

_TAIL_OFF = 0    # producer cursor: total bytes ever written
_HEAD_OFF = 64   # consumer cursor: total bytes ever read (own cache line)
_DATA_OFF = 128

DEFAULT_RING_BYTES = 1 << 20
# backpressure wait while a ring is full: exponential from 20 us so a
# streaming >ring-size frame resumes almost immediately after the
# reader frees space, capped well under a scheduler timeslice
_FULL_SLEEP_MIN = 20e-6
_FULL_SLEEP_MAX = 1e-3

# a writer stuck on a full ring this long gives up with OSError — the
# reader is dead or wedged, and OSError is the transport-failure signal
# every role already handles (TCP's analogue is a refused reconnect)
FULL_RING_TIMEOUT = 20.0

# receiver insurance: with rings attached, a blocking recv re-scans
# them at least this often even without a bell. Bounds the theoretical
# lost-wakeup window of the sender-side doorbell coalescing (a stale
# head read can make a sender skip a bell the receiver needed; on
# x86-TSO the store->load reorder that requires has never been
# observed at Python's instruction granularity, but 4 spurious
# wakeups/s is cheap certainty)
_INSURANCE_S = 0.25

SHM_DIR = "/dev/shm"


class ShmRing:
    """One direction's SPSC byte ring in a named shared-memory segment.

    The segment is a plain file on the shared-memory filesystem,
    ``mmap``\\ ed by both sides — the same object
    ``multiprocessing.shared_memory`` wraps, taken directly because (a)
    py3.10's resource tracker mis-books attach/unlink (KeyError spam in
    the tracker process, and at-exit unlinks racing ours for segments
    of SIGKILLed chaos ranks), and (b) a raw file needs no tracker:
    lifetime is owned explicitly (owner unlink + world sweep).

    Layout: u64 tail @0, u64 head @64 (separate cache lines), data
    @128.  Cursors are monotone byte counts; ``pos = cursor % cap``.
    Each cursor has exactly one writer, and an 8-byte aligned store is
    a single machine store on the platforms this targets — stale reads
    by the other side only ever under-estimate, never corrupt.
    """

    def __init__(self, name: str, nbytes: int = 0,
                 create: bool = False) -> None:
        self.name = name
        self.path = os.path.join(SHM_DIR, name)
        self.owner = create
        if create:
            # a leftover file under this name is a previous incarnation's
            # (deterministic launch.py keys + a SIGKILLed launcher that
            # never swept): we own the writer side of this name, so
            # replace it rather than erroring every first send
            try:
                os.unlink(self.path)
            except OSError:
                pass
            fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_RDWR,
                         0o600)
            try:
                os.ftruncate(fd, _DATA_OFF + nbytes)
                self._mm = mmap.mmap(fd, _DATA_OFF + nbytes)
            finally:
                os.close(fd)
        else:
            fd = os.open(self.path, os.O_RDWR)
            try:
                size = os.fstat(fd).st_size
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
        self._buf = memoryview(self._mm)
        self.cap = len(self._buf) - _DATA_OFF

    def _tail(self) -> int:
        return _CUR.unpack_from(self._buf, _TAIL_OFF)[0]

    def _head(self) -> int:
        return _CUR.unpack_from(self._buf, _HEAD_OFF)[0]

    def avail(self) -> int:
        return self._tail() - self._head()

    @property
    def occupancy(self) -> float:
        return self.avail() / self.cap if self.cap else 0.0

    def write_some(self, mv) -> int:
        """Copy as much of ``mv`` as fits; returns bytes written (0 =
        ring full).  Producer side only."""
        tail = self._tail()
        n = min(self.cap - (tail - self._head()), len(mv))
        if n <= 0:
            return 0
        pos = tail % self.cap
        first = min(n, self.cap - pos)
        buf = self._buf
        buf[_DATA_OFF + pos:_DATA_OFF + pos + first] = mv[:first]
        if n > first:
            buf[_DATA_OFF:_DATA_OFF + n - first] = mv[first:n]
        _CUR.pack_into(buf, _TAIL_OFF, tail + n)  # publish AFTER the copy
        return n

    def read_some(self) -> bytes:
        """Consume everything currently available (b"" when empty).
        Consumer side only."""
        head = self._head()
        n = self._tail() - head
        if n <= 0:
            return b""
        pos = head % self.cap
        first = min(n, self.cap - pos)
        buf = self._buf
        out = bytes(buf[_DATA_OFF + pos:_DATA_OFF + pos + first])
        if n > first:
            out += bytes(buf[_DATA_OFF:_DATA_OFF + n - first])
        _CUR.pack_into(buf, _HEAD_OFF, head + n)  # free AFTER the copy
        return out

    def read_into(self, out: bytearray) -> int:
        """Consume everything currently available straight into ``out``
        (one copy, shared memory -> accumulator); returns bytes read.
        Consumer side only."""
        head = self._head()
        n = self._tail() - head
        if n <= 0:
            return 0
        pos = head % self.cap
        first = min(n, self.cap - pos)
        buf = self._buf
        out += buf[_DATA_OFF + pos:_DATA_OFF + pos + first]
        if n > first:
            out += buf[_DATA_OFF:_DATA_OFF + n - first]
        _CUR.pack_into(buf, _HEAD_OFF, head + n)  # free AFTER the copy
        return n

    def close(self, unlink: Optional[bool] = None) -> None:
        unlink = self.owner if unlink is None else unlink
        try:
            self._buf.release()
            self._mm.close()
        except (OSError, BufferError):
            pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


class Doorbell:
    """Named-FIFO wakeup channel.  The owner (receiver) creates the
    FIFO and holds a non-blocking read end; every producer — ring
    writers in other processes, and the owner's own TCP reader threads
    via the ``notify`` hook — writes one byte after delivering.  Bytes
    accumulate until drained, so a bell rung between the receiver's
    empty-check and its ``select`` is never lost."""

    def __init__(self, path: str, create: bool) -> None:
        self.path = path
        self.owner = create
        self._rfd = -1
        self._wfd = -1
        if create:
            try:
                os.mkfifo(path)
            except FileExistsError:
                pass
            self._rfd = os.open(path, os.O_RDONLY | os.O_NONBLOCK)

    def open_write(self) -> None:
        """Open the write end (raises ENOENT when the peer has no
        fabric, ENXIO when its read end is not up yet)."""
        self._wfd = os.open(self.path, os.O_WRONLY | os.O_NONBLOCK)

    def ring(self) -> None:
        if self._wfd < 0:
            return
        try:
            os.write(self._wfd, b"\x01")
        except BlockingIOError:
            pass  # 64 KiB of undrained bells: wakeup already guaranteed
        except OSError:
            pass  # reader gone: death is signalled via the TCP sentinel

    def probe(self) -> None:
        """Liveness probe: a FIFO whose only reader (the owner) has died
        or closed raises BrokenPipeError on write — the ring fabric's
        fast equivalent of a TCP RST. A SIGSTOPped (gray-failed) owner
        keeps its fds open, so this correctly stays silent for stalls."""
        if self._wfd < 0:
            return
        try:
            os.write(self._wfd, b"\x01")
        except BlockingIOError:
            pass
        except OSError as e:
            raise OSError(
                f"shm doorbell {self.path}: reader gone ({e!r})"
            ) from e

    def drain(self) -> None:
        try:
            while os.read(self._rfd, 4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def wait(self, timeout: Optional[float]) -> bool:
        try:
            r, _, _ = select.select([self._rfd], [], [], timeout)
            return bool(r)
        except (OSError, ValueError):
            # closed mid-wait: don't busy-spin the caller's retry loop
            time.sleep(min(timeout or 0.05, 0.05))
            return False

    def close(self) -> None:
        for fd in (self._rfd, self._wfd):
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
        self._rfd = self._wfd = -1
        if self.owner:
            try:
                os.unlink(self.path)
            except OSError:
                pass


# the TLV-vs-pickle body decision is shared with the multiplexed TCP
# channel plane and lives in the codec module (codec.wire_native_ok)


class _BellBatch(threading.local):
    """Per-thread submit-batch state for the ring fabric: destinations
    whose bells are owed, rung once at flush."""

    depth = 0
    pending: "Optional[dict]" = None


class _RxState:
    """One inbound ring + its partial-frame reassembly buffer."""

    __slots__ = ("ring", "buf")

    def __init__(self, ring: ShmRing) -> None:
        self.ring = ring
        self.buf = bytearray()


class ShmEndpoint:
    """The ring fabric stacked over a :class:`TcpEndpoint`.

    Send path: first send toward a peer probes its doorbell FIFO —
    present means same host + fabric enabled, so a ring is created,
    announced over TCP (``SHM_HELLO``), and all subsequent frames to
    that peer stream through it; absent (cross-host, native daemon,
    plain-TCP peer) means the pair stays on TCP forever, so ordering
    within the pair is preserved (frames never alternate transports).
    Recv path: drain+parse every attached inbound ring into the shared
    inbox, then block on the doorbell — TCP deliveries ring the same
    bell via the endpoint's ``notify`` hook.
    """

    def __init__(self, tcp_ep, key: str,
                 ring_bytes: int = DEFAULT_RING_BYTES) -> None:
        self._tcp = tcp_ep
        self.rank = tcp_ep.rank
        self.key = key
        self.ring_bytes = max(int(ring_bytes), 4096)
        self._tx: dict[int, tuple[ShmRing, Doorbell]] = {}
        self._no_shm: set[int] = set()
        self._dead: set[int] = set()
        self._eof_flushed: set[int] = set()
        self._rx: dict[int, _RxState] = {}
        self._rx_lock = threading.Lock()
        self._attach_lock = threading.Lock()
        self._send_locks: dict[int, threading.Lock] = {}
        self._recv_lock = threading.Lock()
        self._closed = False
        self._tx_stats: dict = {}
        self._rx_stats: dict = {}
        self._g_occ = None
        self._g_wake = None
        self._g_sup = None
        self._h_send = None  # send_s / recv_wait_s histograms — same
        self._h_recv = None  # exposition contract as the TCP endpoint
        self.doorbell_wakeups = 0
        # doorbell coalescing: per-dest ring tail at the last bell we
        # rang (guarded by that dest's send lock). A peer that has not
        # consumed up to that point either still has our byte in its
        # FIFO or is awake mid-drain — both end in a ring scan that
        # sees any newer frame, so the bell write is skipped.
        self._rung: dict[int, int] = {}
        self.doorbell_suppressed = 0
        self.shm_frames_tx = 0
        self.shm_frames_rx = 0
        # submit batching: per-thread deferred doorbells — a reactor
        # tick's burst of N ring writes rings each destination's bell
        # ONCE at submit_flush instead of per frame (the PR 8 named
        # follow-up; composes with the _rung suppression below)
        self._submit = _BellBatch()
        self._bell = Doorbell(self._bell_path(self.rank), create=True)
        self._bell.open_write()  # self-notify end for the TCP hooks
        tcp_ep.notify = self._bell.ring
        tcp_ep.shm_ctl = self._on_hello

    # -- naming --------------------------------------------------------------

    def _ring_name(self, src: int, dst: int) -> str:
        return f"{self.key}.{src}to{dst}"

    def _bell_path(self, rank: int) -> str:
        return os.path.join(SHM_DIR, f"{self.key}.bell.{rank}")

    # -- attribute passthrough (roles and harnesses see one endpoint) --------

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_tcp"), name)

    @property
    def metrics(self):
        return self._tcp.metrics

    @metrics.setter
    def metrics(self, reg) -> None:
        self._tcp.metrics = reg

    # -- pair upgrade --------------------------------------------------------

    def _on_hello(self, m: Msg) -> None:
        """SHM_HELLO from ``m.src`` (TCP reader thread): attach the ring
        that peer just created toward us and start scanning it."""
        src = m.src
        with self._rx_lock:
            if src in self._rx or self._closed:
                return
            try:
                ring = ShmRing(self._ring_name(src, self.rank))
            except (OSError, FileNotFoundError):
                return  # announced then died before we looked: EOF follows
            self._rx[src] = _RxState(ring)
        self._bell.ring()

    def _attach(self, dest: int, connect_grace: float):
        """Try to upgrade the (self, dest) pair to a ring; returns the
        (ring, bell) pair or None (TCP fallback, recorded so the probe
        runs once per peer). Serialized PER DESTINATION: the probe can
        wait up to ~2 s and the HELLO up to the TCP connect grace, and a
        dead peer must not stall other threads' first sends to healthy
        peers (the same isolation the TCP plane's per-dest send locks
        provide)."""
        with self._attach_lock:  # guards the lock map only
            dlock = self._send_locks.setdefault(dest, threading.Lock())
        with dlock:
            tx = self._tx.get(dest)
            if tx is not None:
                return tx
            if dest in self._no_shm:
                return None
            # different advertised host, or a native daemon (binary
            # peer): no fabric there, don't burn the probe grace
            amap = self._tcp.addr_map
            my_host = amap.get(self.rank, ("",))[0]
            if (amap.get(dest, (None,))[0] != my_host
                    or dest in self._tcp.binary_peers
                    or dest == self.rank):
                self._no_shm.add(dest)
                return None
            bell = Doorbell(self._bell_path(dest), create=False)
            # short probe: a peer we can address has already constructed
            # its endpoint (ports publish after bind), so its FIFO exists
            # if it ever will — the grace only covers same-process races,
            # not a peer that simply runs plain TCP
            deadline = time.monotonic() + max(min(connect_grace, 2.0), 0.25)
            while True:
                try:
                    bell.open_write()
                    break
                except OSError:
                    # ENOENT: same host but the peer runs plain TCP (or
                    # is still starting); ENXIO: FIFO exists, reader not
                    # up yet.  Retry within the grace, then TCP forever.
                    if time.monotonic() >= deadline:
                        self._no_shm.add(dest)
                        return None
                    time.sleep(0.02)
            ring = ShmRing(self._ring_name(self.rank, dest),
                           self.ring_bytes, create=True)
            try:
                # announce over TCP: the receiver attaches on this frame,
                # and the connection it rides is the pair's death sentinel
                self._tcp.send(dest, msg(Tag.SHM_HELLO, self.rank),
                               connect_grace)
            except OSError:
                ring.close(unlink=True)
                bell.close()
                raise
            tx = (ring, bell)
            self._tx[dest] = tx
            return tx

    # -- send ----------------------------------------------------------------

    def send(self, dest: int, m: Msg, connect_grace: float = 15.0) -> None:
        if dest in self._dead:
            raise OSError(f"shm fabric: rank {dest} is dead (PEER_EOF seen)")
        tx = self._tx.get(dest)
        if tx is None:
            tx = self._attach(dest, connect_grace)
            if tx is None:
                self._tcp.send(dest, m, connect_grace)
                return
        ring, bell = tx
        # scatter-gather TLV when every field has a wire id (the whole
        # put/fetch hot path), restricted pickle otherwise; the reader
        # discriminates on the first body byte exactly like the TCP plane
        if wire_native_ok(m):
            parts = encode_binary_iov(m)
        else:
            parts = [pickle.dumps(m, protocol=pickle.HIGHEST_PROTOCOL)]
        nbody = sum(len(p) for p in parts)
        reg = self._tcp.metrics
        t0 = time.monotonic() if reg is not None else 0.0
        with self._send_locks[dest]:
            self._write_frame(ring, bell, dest, nbody, parts)
        self.shm_frames_tx += 1
        if reg is not None:
            st = self._tx_stats.get(m.tag)
            if st is None:
                st = self._tx_stats[m.tag] = (
                    reg.counter("tx_msgs", tag=m.tag.name),
                    reg.counter("tx_bytes", tag=m.tag.name),
                )
            st[0].inc()
            st[1].inc(_LEN.size + nbody)
            # whole-path send latency (ring admission incl. full-ring
            # waits) — the TCP endpoint's send_s, same exposition
            if self._h_send is None:
                self._h_send = reg.histogram("send_s")
            self._h_send.observe(time.monotonic() - t0)
            # suppression is SENDER-side state: export it here, not
            # only from the rx drain (a mostly-sending rank would
            # otherwise scrape a stale 0 forever)
            if self._g_sup is None:
                self._g_sup = reg.gauge("shm_doorbell_suppressed")
            self._g_sup.set(self.doorbell_suppressed)

    def _write_frame(self, ring: ShmRing, bell: Doorbell, dest: int,
                     nbody: int, parts: list) -> None:
        """Stream one length-prefixed frame into the ring, waiting for
        the reader when full (frames larger than the ring flow through
        it in ring-sized installments).

        ONE wakeup per frame, coalesced: the bell rings after the whole
        frame lands (not per segment — a TLV frame used to ring once
        per header/field/payload part), and even that ring is skipped
        when the peer is known-awake: our previous bell's byte is
        unconsumed (head behind the tail it advertised), so the drain
        it triggers will pick this frame up too. The full-ring wait
        needs no extra bell — ``probe()`` writes a byte each lap, which
        doubles as the wakeup for the bytes already streamed. A stale
        head read can only over-skip, never over-ring; recv()'s
        insurance re-scan bounds the (never-observed, theoretical
        store-order) lost-wakeup window."""
        deadline = None
        sleep_s = _FULL_SLEEP_MIN
        for seg in (_LEN.pack(nbody), *parts):
            mv = memoryview(seg)
            while mv.nbytes:
                n = ring.write_some(mv)
                if n:
                    mv = mv[n:]
                    deadline = None
                    sleep_s = _FULL_SLEEP_MIN
                    continue
                if dest in self._dead or self._closed:
                    raise OSError(
                        f"shm fabric: ring to rank {dest} abandoned "
                        f"(peer dead or endpoint closed)"
                    )
                # fast death detection while blocked on a full ring: a
                # dead peer's doorbell has no reader and the probe
                # raises (TCP's RST analogue) — without this, a sender
                # whose peer was SIGKILLed waits out the full-ring
                # backstop on EVERY retry (observed: an abort-policy
                # worker kill taking 4 x 20 s to classify)
                bell.probe()
                now = time.monotonic()
                if deadline is None:
                    deadline = now + FULL_RING_TIMEOUT
                elif now >= deadline:
                    raise OSError(
                        f"shm fabric: ring to rank {dest} full for "
                        f"{FULL_RING_TIMEOUT}s (reader wedged or dead)"
                    )
                time.sleep(sleep_s)
                sleep_s = min(sleep_s * 2, _FULL_SLEEP_MAX)
        st = self._submit
        if st.depth > 0 and st.pending is not None:
            # submit batch: the bell is owed, not rung — submit_flush
            # rings each pending destination once (the frame is already
            # IN the ring, so the deferral moves only the wakeup)
            st.pending[dest] = (ring, bell)
            return
        self._ring_bell(dest, ring, bell)

    def _ring_bell(self, dest: int, ring: ShmRing, bell: Doorbell) -> None:
        tail = ring._tail()
        last = self._rung.get(dest, -1)
        if last >= 0 and ring._head() < last:
            self.doorbell_suppressed += 1
        else:
            bell.ring()
            self._rung[dest] = tail

    # -- submit batching ------------------------------------------------------

    def submit_begin(self) -> None:
        st = self._submit
        st.depth += 1
        if st.pending is None:
            st.pending = {}
        self._tcp.submit_begin()

    def submit_flush(self) -> None:
        st = self._submit
        if st.depth > 0:
            st.depth -= 1
        if st.depth == 0 and st.pending:
            pending, st.pending = st.pending, {}
            for dest, (ring, bell) in pending.items():
                self._ring_bell(dest, ring, bell)
        self._tcp.submit_flush()

    # -- recv ----------------------------------------------------------------

    def _decode(self, src: int, body: bytes) -> Optional[Msg]:
        try:
            if body[:1] == b"\x01":
                return decode_binary(body)
            m = loads_restricted(body)
            if not isinstance(m, Msg):
                raise pickle.UnpicklingError(
                    f"frame unpickled to {type(m).__name__}, not Msg"
                )
            return m
        except Exception as e:  # noqa: BLE001 — a bad frame must be
            import sys  # diagnosable, not a silent reader death

            print(
                f"[adlb shm rank {self.rank}] dropping undecodable ring "
                f"frame from {src} ({len(body)}B): {e!r}",
                file=sys.stderr,
            )
            return None

    def _parse(self, src: int, st: _RxState) -> int:
        buf = st.buf
        off = 0
        delivered = 0
        reg = self._tcp.metrics
        while True:
            if len(buf) - off < _LEN.size:
                break
            (ln,) = _LEN.unpack_from(buf, off)
            if len(buf) - off - _LEN.size < ln:
                break  # frame still streaming in
            body = bytes(buf[off + _LEN.size:off + _LEN.size + ln])
            off += _LEN.size + ln
            m = self._decode(src, body)
            if m is None:
                continue
            if reg is not None:
                rst = self._rx_stats.get(m.tag)
                if rst is None:
                    rst = self._rx_stats[m.tag] = (
                        reg.counter("rx_msgs", tag=m.tag.name),
                        reg.counter("rx_bytes", tag=m.tag.name),
                    )
                rst[0].inc()
                rst[1].inc(_LEN.size + len(body))
            self._tcp.inbox.put(m)
            delivered += 1
        if off:
            del buf[:off]
        return delivered

    def _drain_rings(self) -> int:
        with self._recv_lock:
            with self._rx_lock:
                items = list(self._rx.items())
            got = 0
            occ = 0.0
            for src, st in items:
                occ = max(occ, st.ring.occupancy)
                if st.ring.read_into(st.buf):
                    got += self._parse(src, st)
            reg = self._tcp.metrics
            if reg is not None and items:
                if self._g_occ is None:
                    self._g_occ = reg.gauge("shm_ring_occupancy")
                    self._g_wake = reg.gauge("shm_doorbell_wakeups")
                    self._g_sup = reg.gauge("shm_doorbell_suppressed")
                self._g_occ.set(occ)
                self._g_wake.set(self.doorbell_wakeups)
                self._g_sup.set(self.doorbell_suppressed)
            self.shm_frames_rx += got
            if got > 1:
                # a second consumer thread may be parked in select while
                # we return only one of these frames; one insurance bell
                # keeps the inbox drain prompt without a busy loop
                self._bell.ring()
            return got

    # brief ring-poll spin before parking in select: on multi-core
    # hosts the peer's next frame typically lands within microseconds,
    # and the spin saves the full futex wakeup; on a single-core host
    # spinning only steals the sender's timeslice, so it is disabled
    _SPIN_S = 50e-6 if (os.cpu_count() or 1) > 1 else 0.0

    def recv(self, timeout: Optional[float] = None) -> Optional[Msg]:
        deadline = None if timeout is None else time.monotonic() + timeout
        inbox = self._tcp.inbox
        reg = self._tcp.metrics
        t0 = time.monotonic() if reg is not None else 0.0
        spun = False
        while True:
            # inbox first: under bursts the previous drain already
            # parsed a batch, and re-scanning every ring per message is
            # the dominant per-op cost of the recv path (the PEER_EOF
            # branch below still forces its own drain, so the ordering
            # fix is unaffected)
            try:
                m = inbox.get_nowait()
            except queue.Empty:
                self._drain_rings()
                try:
                    m = inbox.get_nowait()
                except queue.Empty:
                    m = None
            if m is not None:
                if m.tag is Tag.PEER_EOF:
                    # sends to a dead shm peer must fail like TCP's
                    # refused reconnect, not fill a ring nobody reads
                    self._dead.add(m.src)
                    if m.src not in self._eof_flushed:
                        # CROSS-CHANNEL ORDERING: the peer's last ring
                        # frames (e.g. FA_LOCAL_APP_DONE) were written
                        # before the close that raised this EOF, but the
                        # EOF rides the TCP reader thread and can enter
                        # the inbox first — delivering it now would read
                        # as "died before finalize" and abort the world.
                        # Drain the rings once more (everything written
                        # happens-before the close, so it is visible
                        # now; a torn mid-write tail cannot parse and is
                        # rightly ignored) and requeue the EOF BEHIND
                        # those frames.
                        self._eof_flushed.add(m.src)
                        self._drain_rings()
                        inbox.put(m)
                        continue
                if reg is not None:
                    # wait-for-message latency (observed only when a
                    # message arrived) — the TCP endpoint's recv_wait_s
                    if self._h_recv is None:
                        self._h_recv = reg.histogram("recv_wait_s")
                    self._h_recv.observe(time.monotonic() - t0)
                return m
            if self._closed:
                return None
            if self._SPIN_S and not spun and self._rx:
                spun = True
                with self._rx_lock:
                    rings = [st.ring for st in self._rx.values()]
                end = time.monotonic() + self._SPIN_S
                while time.monotonic() < end:
                    if any(r.avail() for r in rings):
                        break
                continue
            if deadline is None:
                remaining = None
            else:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
            if self._rx and (remaining is None or remaining > _INSURANCE_S):
                remaining = _INSURANCE_S  # bounded re-scan (see above)
            if self._bell.wait(remaining):
                self.doorbell_wakeups += 1
                self._bell.drain()

    def backlog(self) -> int:
        b = self._tcp.backlog()
        with self._rx_lock:
            for st in self._rx.values():
                if st.buf or st.ring.avail():
                    b += 1
        return b

    # -- teardown ------------------------------------------------------------

    def close(self) -> None:
        self._closed = True
        self._bell.ring()  # wake any recv blocked in select
        try:
            self._tcp.close()
        finally:
            with self._attach_lock:
                for ring, bell in self._tx.values():
                    # no unlink here even though we created it: the
                    # receiver may not have processed our SHM_HELLO yet,
                    # and unlinking would strand the final frames it
                    # still has to attach-and-drain (the finalize race).
                    # The receiver unlinks on ITS close; the world sweep
                    # (cleanup_world) catches receivers that died first.
                    ring.close(unlink=False)
                    bell.close()
                self._tx.clear()
            with self._rx_lock:
                for st in self._rx.values():
                    st.ring.close(unlink=True)
                self._rx.clear()
            self._bell.close()


# ----------------------------------------------------------- world plumbing


def new_world_key() -> str:
    """A fresh namespace for one world's segments/FIFOs (spawn_world)."""
    return f"adlb{uuid.uuid4().hex[:12]}"


def key_for_rendezvous(path: str) -> str:
    """Deterministic key shared by every launcher (and joined client) of
    a rendezvous-directory world."""
    import hashlib

    h = hashlib.sha1(os.path.abspath(path).encode()).hexdigest()[:12]
    return f"adlb{h}"


def cleanup_world(key: str) -> None:
    """Best-effort sweep of a world's leftover segments and FIFOs —
    SIGKILLed ranks (chaos legs) never unlink what they own."""
    if not key:
        return
    for path in glob.glob(os.path.join(SHM_DIR, f"{key}.*")):
        try:
            os.unlink(path)
        except OSError:
            pass


def shm_headroom() -> int:
    """Free bytes on the shared-memory filesystem (0 when absent)."""
    try:
        st = os.statvfs(SHM_DIR)
        return st.f_bavail * st.f_frsize
    except OSError:
        return 0


def shm_available(min_headroom: int = 64 << 20) -> bool:
    """Can this host run the ring fabric? (segment + FIFO probe, plus a
    headroom floor so a nearly-full /dev/shm degrades to TCP instead of
    failing worlds mid-run). Restricted to total-store-order ISAs: the
    ring's publish discipline (data copy, then cursor store, no explicit
    barrier) is only sound under TSO — on weaker memory models (aarch64
    etc.) ``fabric="auto"`` stays on TCP rather than risking silently
    reordered payload bytes."""
    import platform

    if platform.machine().lower() not in ("x86_64", "amd64", "i686",
                                          "i386"):
        return False
    if shm_headroom() < min_headroom:
        return False
    name = f"adlbprobe{os.getpid():x}{uuid.uuid4().hex[:6]}"
    try:
        seg = ShmRing(name, 4096, create=True)
        seg.close()  # owner: unlinks
        fifo = os.path.join(SHM_DIR, f"{name}.fifo")
        os.mkfifo(fifo)
        os.unlink(fifo)
        return True
    except (OSError, ValueError):
        return False


def resolve_fabric(cfg) -> str:
    """Which process-world fabric to run: an explicit ``Config(fabric)``
    wins; ``"auto"`` honors the ``ADLB_FABRIC`` env override (the CI shm
    leg's hook) and otherwise upgrades to shm whenever the host can."""
    f = getattr(cfg, "fabric", "auto")
    if f != "auto":
        return f
    env = os.environ.get("ADLB_FABRIC", "").strip().lower()
    if env in ("shm", "tcp"):
        return env
    return "shm" if shm_available() else "tcp"


def maybe_shm(ep, cfg, key: Optional[str]):
    """Stack the ring fabric over a TcpEndpoint when the resolved fabric
    is shm (the single hook the world harnesses call)."""
    if not key or resolve_fabric(cfg) != "shm":
        return ep
    return ShmEndpoint(ep, key,
                       ring_bytes=getattr(cfg, "shm_ring_bytes",
                                          DEFAULT_RING_BYTES))
