"""Swappable line sink shared by the observability modules (stats, debug).

Each module owns its own :class:`Sink` instance so tests can capture one
stream without touching the other; the default destination is stderr, like
the reference's aprintf output."""

from __future__ import annotations

import sys
from typing import Callable, Optional


class Sink:
    def __init__(self) -> None:
        self._fn: Optional[Callable[[str], None]] = None

    def set(self, fn: Optional[Callable[[str], None]]) -> None:
        """Redirect output (tests); None restores stderr."""
        self._fn = fn

    def emit(self, line: str) -> None:
        if self._fn is not None:
            self._fn(line)
        else:
            print(line, file=sys.stderr, flush=True)
