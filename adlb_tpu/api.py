"""Public API.

Two surfaces:

* :class:`AdlbContext` — the per-rank object handed to application code, with
  methods mirroring the reference's public C API one-for-one
  (``ADLB_Put/Reserve/Ireserve/Get_reserved/...``, reference
  ``include/adlb/adlb.h:42-88``) in Pythonic form.
* :func:`run_world` — spins up a world in-process (ranks as threads, the
  analogue of ``mpiexec -n k`` for the reference's examples) and runs an app
  function on every app rank. Multi-process/multi-host worlds use the TCP
  transport entry points instead (``adlb_tpu.runtime.transport_tcp``).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Optional, Sequence

from adlb_tpu.runtime.client import Client
from adlb_tpu.runtime.debug_server import DebugServer
from adlb_tpu.runtime.server import Server
from adlb_tpu.runtime.transport import InProcFabric
from adlb_tpu.runtime.world import Config, WorldSpec
from adlb_tpu.types import ADLB_SUCCESS, AdlbAborted, InfoKey, WorkHandle


class AdlbContext:
    """Per-app-rank handle: the reference's client API surface."""

    def __init__(self, client: Client) -> None:
        self._c = client

    @property
    def rank(self) -> int:
        return self._c.rank

    @property
    def num_app_ranks(self) -> int:
        return self._c.world.num_app_ranks

    @property
    def world(self) -> WorldSpec:
        return self._c.world

    # The reference API, in order of include/adlb/adlb.h:
    def put(
        self,
        payload: bytes,
        work_type: int,
        work_prio: int = 0,
        target_rank: int = -1,
        answer_rank: int = -1,
    ) -> int:
        return self._c.put(payload, work_type, work_prio, target_rank, answer_rank)

    def reserve(self, req_types: Optional[Sequence[int]] = None):
        return self._c.reserve(req_types)

    def ireserve(self, req_types: Optional[Sequence[int]] = None):
        return self._c.ireserve(req_types)

    def get_reserved(self, handle: WorkHandle):
        return self._c.get_reserved(handle)

    def get_work(self, req_types: Optional[Sequence[int]] = None):
        """Fused blocking reserve+get: one round trip when the unit is local
        and prefix-free (no reference analogue)."""
        return self._c.get_work(req_types)

    def get_work_batch(
        self,
        req_types: Optional[Sequence[int]] = None,
        max_units: int = 8,
    ):
        """Fused reserve+get of up to max_units LOCAL prefix-free units in
        one round trip (no reference analogue); returns (rc, [GotWork])."""
        return self._c.get_work_batch(req_types, max_units)

    def get_work_stream(
        self, req_types: Optional[Sequence[int]] = None, depth: int = 2
    ):
        """Pipelined consumer: an iterator of GotWork keeping up to
        ``depth`` fused reserves in flight, so the next unit's delivery
        overlaps the current unit's compute (no reference analogue).
        Ends at NO_MORE_WORK / DONE_BY_EXHAUSTION (code in ``.rc``);
        use as a context manager or call ``.close()`` if abandoning the
        stream early::

            with ctx.get_work_stream([TYPE], depth=4) as stream:
                for work in stream:
                    process(work.payload)
        """
        return self._c.get_work_stream(req_types, depth)

    def get_reserved_timed(self, handle: WorkHandle):
        return self._c.get_reserved_timed(handle)

    def iput(
        self,
        payload: bytes,
        work_type: int,
        work_prio: int = 0,
        target_rank: int = -1,
        answer_rank: int = -1,
    ) -> int:
        """Pipelined put (no reference analogue): streams the request and
        settles accept/reject at flush_puts(). A producer is then bounded by
        bandwidth, not one round trip per unit."""
        return self._c.iput(payload, work_type, work_prio, target_rank,
                            answer_rank)

    def flush_puts(self) -> int:
        return self._c.flush_puts()

    def begin_batch_put(self, common_buf: bytes) -> int:
        return self._c.begin_batch_put(common_buf)

    def end_batch_put(self) -> int:
        return self._c.end_batch_put()

    def extend_lease(self, handle: WorkHandle) -> int:
        """Renew this rank's lease on a reserved-but-unfetched unit
        (**extension**, Config(lease_timeout_s) > 0): long units opt out
        of lease expiry explicitly instead of raising the whole world's
        timeout. Fire-and-forget; an already-expired lease stays expired
        (the fetch answers the retriable fencing code)."""
        return self._c.extend_lease(handle)

    def get_quarantined(self):
        """(rc, records): the dead-letter quarantine — units moved aside
        after exhausting Config(max_unit_retries), as plain dicts with
        payload, metadata, attempt count, and the holding server
        (**extension**; also served by the ops endpoint's /deadletter)."""
        return self._c.get_quarantined()

    def set_problem_done(self) -> int:
        return self._c.set_problem_done()

    # -- job namespaces (service mode; **extension** — the reference
    # binds one world to one job): submit a namespace on the running
    # fleet, bind ranks to it, drain/kill it from any rank or over the
    # ops endpoint's /jobs control plane.

    @property
    def job(self) -> int:
        """The namespace this rank is attached to (0 = default)."""
        return self._c.job

    def detach_world(self) -> int:
        """Cleanly LEAVE a running world (**extension** — elastic
        membership): the master drops this rank from every server's
        membership under a fresh fleet epoch, leases drain, and
        exhaustion/END counting forgets the rank. After a successful
        detach the context is dead (finalize is a no-op; just close).
        Distinct from :meth:`attach`, which binds a JOB namespace."""
        return self._c.detach()

    def attach(self, job_id: int) -> "AdlbContext":
        """Bind this rank to a job namespace; returns self so app code
        reads naturally as ``ctx = ctx.attach(job_id)``. Raises on a
        control-plane refusal."""
        rc = self._c.attach(job_id)
        if rc != ADLB_SUCCESS:
            from adlb_tpu.types import AdlbError

            raise AdlbError(f"attach({job_id}) refused (rc={rc})")
        return self

    def submit_job(self, name: str = "",
                   quota_bytes: int = 0) -> tuple[int, int]:
        """(rc, job_id): create a namespace (per-server byte quota
        enforced at put with ADLB_BACKOFF; 0 = unlimited)."""
        return self._c.submit_job(name, quota_bytes)

    def drain_job(self, job_id: int) -> tuple[int, int]:
        return self._c.drain_job(job_id)

    def kill_job(self, job_id: int) -> tuple[int, int]:
        return self._c.kill_job(job_id)

    def job_status(self, job_id: int):
        """(rc, status dict from the master's job table)."""
        return self._c.job_status(job_id)

    def info_num_work_units(self, work_type: int):
        return self._c.info_num_work_units(work_type)

    def info_get(self, key) -> tuple[int, float]:
        return self._c.info_get(int(key))

    def checkpoint(self, path_prefix: str) -> tuple[int, int]:
        return self._c.checkpoint(path_prefix)

    def abort(self, code: int) -> None:
        self._c.abort(code)

    # app<->app messaging: the reference hands app code a dedicated
    # communicator (app_comm from ADLB_Init, reference src/adlb.c:256,318)
    # for ordinary point-to-point traffic next to ADLB calls (c1.c's
    # TAG_B_ANSWER flow); these are its MPI_Send/Iprobe/Recv equivalents.
    def app_send(self, dest_app_rank: int, payload, apptag: int = 0) -> None:
        self._c.app_send(dest_app_rank, payload, apptag)

    def app_iprobe(self, apptag: Optional[int] = None,
                   src: Optional[int] = None) -> bool:
        return self._c.app_iprobe(apptag, src)

    def app_recv(self, apptag: Optional[int] = None, src: Optional[int] = None,
                 timeout: Optional[float] = None):
        return self._c.app_recv(apptag, src, timeout)


@dataclasses.dataclass
class WorldResult:
    """What run_world returns: per-app-rank results and per-server stats."""

    app_results: dict[int, Any]
    server_stats: dict[int, dict[int, float]]
    aborted: bool
    exception: Optional[BaseException] = None
    # merged Chrome-trace events when Config(trace=True) (the reference's
    # MPE output, reference src/adlb_prof.c:46-74)
    trace_events: list[dict] = dataclasses.field(default_factory=list)
    # the watchdog instance when use_debug_server=True (its aggregates and
    # printed per-interval summary lines are inspectable post-run)
    debug_server: Optional[Any] = None
    # app ranks that died mid-run and were absorbed by
    # Config(on_worker_failure="reclaim") — the world completed around
    # them, so they have no entry in app_results
    casualties: list[int] = dataclasses.field(default_factory=list)
    # server ranks that died mid-run and were absorbed by
    # Config(on_server_failure="failover"): their pool shard replayed at
    # the ring-successor buddy, which also took over their app ranks
    server_casualties: list[int] = dataclasses.field(default_factory=list)
    # units moved to the dead-letter quarantine (retry budget exhausted,
    # Config(max_unit_retries) > 0) — summed over surviving servers'
    # InfoKey.QUARANTINED, same conservation contract as FAILOVER_LOST:
    # every unit is completed, re-executed, or counted here
    quarantined: int = 0

    def save_trace(self, path: str) -> None:
        from adlb_tpu.runtime.trace import save_chrome_trace

        save_chrome_trace(self.trace_events, path)

    def info_get(self, key: InfoKey) -> float:
        """Aggregate a stats key over servers the way the reference's
        examples read Info_get per server rank (max over servers)."""
        return max((s.get(int(key), 0.0) for s in self.server_stats.values()),
                   default=0.0)


class JoinedWorld:
    """Context manager for an app rank joined to an externally launched
    world (see :mod:`adlb_tpu.runtime.launch`): finalizes the client and
    closes the endpoint on exit."""

    def __init__(self, ctx: AdlbContext, ep) -> None:
        self.ctx = ctx
        self._ep = ep

    def __enter__(self) -> AdlbContext:
        return self.ctx

    def __exit__(self, exc_type, exc, tb) -> None:
        # finalize even when the app body raised: without FA_LOCAL_APP_DONE
        # the shutdown ring never completes and the whole world hangs
        try:
            self.ctx._c.finalize()
        except Exception:  # teardown races (home server gone) are benign
            pass
        finally:
            self._ep.close()


def join_world(
    types: Sequence[int],
    nservers: Optional[int] = None,
    cfg: Optional[Config] = None,
    rank: Optional[int] = None,
    rendezvous: Optional[str] = None,
) -> JoinedWorld:
    """Join an externally launched world as an app rank (the Python analogue
    of the C client's ADLB_Init env contract). Reads ``ADLB_RANK`` /
    ``ADLB_RENDEZVOUS`` / ``ADLB_NUM_SERVERS`` (and ``ADLB_SERVER_IMPL``)
    when not given:

        with join_world(types=[1]) as ctx:
            ctx.put(b"...", 1)

    The rendezvous file lists every world rank as ``rank host port`` lines;
    this process binds its own rank's port. An explicit ``nservers`` that
    disagrees with the launcher's exported value would silently misroute
    every message, so a mismatch is rejected.
    """
    import os

    from adlb_tpu.runtime.transport_tcp import TcpEndpoint

    env_ns = os.environ.get("ADLB_NUM_SERVERS")
    if nservers is None:
        if env_ns is None:
            raise ValueError("nservers not given and ADLB_NUM_SERVERS not set")
        nservers = int(env_ns)
    elif env_ns is not None and int(env_ns) != nservers:
        raise ValueError(
            f"nservers={nservers} disagrees with the launcher's "
            f"ADLB_NUM_SERVERS={env_ns}"
        )
    attach = rank is None and os.environ.get(
        "ADLB_ATTACH", ""
    ).strip().lower() in ("1", "on", "true", "yes")
    if not attach:
        rank = int(os.environ["ADLB_RANK"]) if rank is None else rank
    path = rendezvous or os.environ["ADLB_RENDEZVOUS"]
    addr_map: dict[int, tuple[str, int]] = {}
    with open(path) as f:
        for line in f:
            r, h, p = line.split()
            addr_map[int(r)] = (h, int(p))
    if cfg is None:
        fault_spec = None
        if os.environ.get("ADLB_FAULT_SPEC"):
            import json

            fault_spec = json.loads(os.environ["ADLB_FAULT_SPEC"])
        cfg = Config(
            server_impl=os.environ.get("ADLB_SERVER_IMPL", "python"),
            on_worker_failure=os.environ.get(
                "ADLB_ON_WORKER_FAILURE", "abort"
            ),
            on_server_failure=os.environ.get(
                "ADLB_ON_SERVER_FAILURE", "abort"
            ),
            lease_timeout_s=float(
                os.environ.get("ADLB_LEASE_TIMEOUT_S", "0") or 0
            ),
            fault_spec=fault_spec,
        )
    world = WorldSpec(
        nranks=len(addr_map), nservers=nservers, types=tuple(types)
    )
    binary_peers = (
        set(world.server_ranks) if cfg.server_impl == "native" else None
    )
    from adlb_tpu.runtime.codec import select_codec

    select_codec(cfg.codec)
    if attach:
        # elastic membership (ADLB_ATTACH=1, launch.py --attach): this
        # process is a NEW rank joining the running world — negotiate a
        # rank id + home server from the master instead of reading
        # ADLB_RANK. Attached ranks ride per-pair TCP (the launcher's
        # brokers route only the static world).
        return attach_world(
            world, cfg,
            master_addr=addr_map[world.master_server_rank],
        )
    mux_addr = None
    broker_env = os.environ.get("ADLB_BROKER_ADDR", "").strip()
    if cfg.tcp_mux != "off" and broker_env:
        # the launcher published this host's channel broker: one
        # data-plane socket to it instead of one per peer
        h, _, p = broker_env.rpartition(":")
        mux_addr = (h, int(p))
    elif cfg.tcp_mux == "on":
        # no silent fallback for an explicit ask (the codec="c" rule)
        raise ValueError(
            "tcp_mux='on' requires a broker-running harness "
            "(spawn_world, or the launcher's broker publication via "
            "ADLB_BROKER_ADDR — is the launcher running with the mux "
            "enabled?)"
        )
    mux_ranks = int(os.environ.get("ADLB_MUX_RANKS", "0") or 0) \
        or world.nranks
    ep = TcpEndpoint(rank, addr_map, binary_peers=binary_peers,
                     mux=mux_addr, mux_ranks=mux_ranks,
                     compress_min=cfg.compress_min_bytes)
    # shm ring fabric toward same-host ranks (the launcher exports
    # ADLB_FABRIC/ADLB_SHM_KEY; a bare join derives the key from the
    # rendezvous directory, so all parties of one world agree)
    from adlb_tpu.runtime.transport_shm import (
        key_for_rendezvous,
        maybe_shm,
        resolve_fabric,
    )

    if resolve_fabric(cfg) == "shm":
        shm_key = os.environ.get("ADLB_SHM_KEY") or key_for_rendezvous(
            os.path.dirname(os.path.abspath(path))
        )
        ep = maybe_shm(ep, cfg, shm_key)
    if cfg.fault_spec:
        from adlb_tpu.runtime.faults import maybe_wrap

        ep = maybe_wrap(ep, cfg, world)
    return JoinedWorld(AdlbContext(Client(world, cfg, ep)), ep)


def attach_world(
    world,
    cfg: Optional[Config] = None,
    *,
    fabric=None,
    master_addr=None,
    abort_event=None,
) -> JoinedWorld:
    """Attach a NEW app rank to a RUNNING world (**extension** — elastic
    membership; the reference fixes the world at ADLB_Init). The master
    allocates a rank id + home server under a fresh fleet epoch; the
    returned JoinedWorld finalizes on exit, or call
    ``ctx.detach_world()`` to leave mid-run::

        with attach_world(world, cfg, fabric=fabric) as ctx:
            ctx.put(b"...", 1)

    Exactly one of ``fabric`` (in-proc worlds) or ``master_addr`` (TCP:
    the master server's (host, port)) selects the transport. Python
    servers only."""
    from adlb_tpu.runtime.membership import attach_app

    return attach_app(world, cfg or Config(), fabric=fabric,
                      master_addr=master_addr, abort_event=abort_event)


def run_world(
    num_app_ranks: int,
    nservers: int,
    types: Sequence[int],
    app_fn: Callable[[AdlbContext], Any],
    cfg: Optional[Config] = None,
    use_debug_server: bool = False,
    timeout: float = 120.0,
) -> WorldResult:
    """Run a complete world in one process, one thread per rank."""
    cfg = cfg or Config()
    world = WorldSpec(
        nranks=num_app_ranks + nservers + (1 if use_debug_server else 0),
        nservers=nservers,
        types=tuple(types),
        use_debug_server=use_debug_server,
    )
    fabric = InProcFabric(world.nranks)
    app_results: dict[int, Any] = {}
    server_stats: dict[int, dict[int, float]] = {}
    trace_events: list[dict] = []
    errors: list[BaseException] = []
    casualties: list[int] = []
    server_casualties: list[int] = []
    lock = threading.Lock()

    from adlb_tpu.runtime.faults import maybe_wrap
    from adlb_tpu.types import HomeServerLostError

    def app_main(rank: int) -> None:
        client = Client(world, cfg,
                        maybe_wrap(fabric.endpoint(rank), cfg, world),
                        fabric.abort_event)
        ctx = AdlbContext(client)
        try:
            result = app_fn(ctx)
            with lock:
                app_results[rank] = result
        except AdlbAborted:
            pass
        except BaseException as e:  # noqa: BLE001 — surfaced via WorldResult
            if cfg.on_worker_failure == "reclaim" and isinstance(
                e, HomeServerLostError
            ):
                # a fault-injected disconnect (or real connectivity loss —
                # the client raises HomeServerLostError for ANY peer that
                # stays unreachable) is a CASUALTY under the reclaim
                # policy: the world keeps running without this rank.
                # Application errors (including the app's own OSErrors)
                # still surface as world failures.
                with lock:
                    casualties.append(rank)
            else:
                with lock:
                    errors.append(e)
                fabric.abort_event.set()
        finally:
            try:
                client.finalize()
            except Exception:  # dead endpoint at teardown: benign
                pass
            if client.tracer is not None:
                with lock:
                    trace_events.extend(client.tracer.events)

    def server_main(rank: int) -> None:
        server = Server(world, cfg,
                        maybe_wrap(fabric.endpoint(rank), cfg, world),
                        fabric.abort_event)
        try:
            server.run()
            with lock:
                if server.died:
                    # fault-injected server death absorbed by
                    # on_server_failure="failover": the buddy took over;
                    # this thread exits as the casualty, not an error
                    server_casualties.append(rank)
                else:
                    server_stats[rank] = server.finalize_stats()
        except BaseException as e:  # noqa: BLE001
            with lock:
                errors.append(e)
            fabric.abort_event.set()
        finally:
            if server.tracer is not None:
                # server handler/balancer spans join the same merged
                # Chrome-trace stream as client API calls (pid = role)
                with lock:
                    trace_events.extend(server.tracer.events)

    debug_servers: list[DebugServer] = []

    def debug_main(rank: int) -> None:
        ds = DebugServer(world, cfg, fabric.endpoint(rank), fabric.abort_event)
        debug_servers.append(ds)
        ds.run()

    threads: list[threading.Thread] = []
    # servers (and the debug server) start BEFORE app ranks: app threads
    # begin with protocol round trips, and every server thread still
    # being spawned is pure startup latency charged to the apps'
    # makespans (messages would queue correctly either way — this is a
    # latency ordering, not a correctness one)
    ordered = [r for r in range(world.nranks) if not world.is_app(r)] + [
        r for r in range(world.nranks) if world.is_app(r)
    ]
    for rank in ordered:
        if world.is_app(rank):
            target = app_main
        elif world.is_server(rank):
            target = server_main
        else:
            target = debug_main
        t = threading.Thread(target=target, args=(rank,), daemon=True,
                             name=f"adlb-rank-{rank}")
        threads.append(t)
        t.start()

    import time as _time

    deadline = _time.monotonic() + timeout
    for t in threads:
        t.join(timeout=max(deadline - _time.monotonic(), 0.0))
        if t.is_alive():
            fabric.abort_event.set()
            for t2 in threads:
                t2.join(timeout=5.0)
            errors.append(TimeoutError(f"world did not finish within {timeout}s"))
            break

    with lock:  # a timed-out client thread may still be appending
        trace_events = sorted(trace_events, key=lambda e: e["ts"])
    result = WorldResult(
        app_results=app_results,
        server_stats=server_stats,
        aborted=fabric.abort_event.is_set(),
        exception=errors[0] if errors else None,
        trace_events=trace_events,
        debug_server=debug_servers[0] if debug_servers else None,
        casualties=sorted(casualties),
        server_casualties=sorted(server_casualties),
        quarantined=int(sum(
            s.get(int(InfoKey.QUARANTINED), 0)
            for s in server_stats.values()
        )),
    )
    if errors:
        raise errors[0]
    return result
