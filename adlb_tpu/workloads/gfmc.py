"""GFMC-style A/B/C/D work-package economy with self-validating counts.

Mirrors the reference's c4 mini-app (reference ``examples/c4.c``), the
abstraction of the GFMC nuclear Monte Carlo production code
(``examples/README-gfmc.txt``): a master emits type-A packages; workers
expand each A into B packages; each B spawns C packages whose *answers* are
routed back (via ``answer_rank`` targeting) to the rank that owns the B,
which combines them into one D result for the master. The expected number of
packages of every type is computable up front, and the run aborts if the
processed counts do not match (reference ``examples/c4.c:176-180,495-502``) —
making this a correctness test of the entire Put/Reserve/answer economy.
"""

from __future__ import annotations

import dataclasses
import struct
import time
from typing import Optional

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

TYPE_A, TYPE_B, TYPE_C, TYPE_C_ANSWER, TYPE_D = 1, 2, 3, 4, 5
PRIO_A, PRIO_B, PRIO_C, PRIO_ANSWER = 1, 2, 3, 9


@dataclasses.dataclass
class GfmcResult:
    ok: bool
    counts: dict[str, int]
    expected: dict[str, int]
    elapsed: float
    tasks_per_sec: float
    tasks_processed: int = 0


def run(
    num_a: int = 6,
    bs_per_a: int = 4,
    cs_per_b: int = 3,
    num_app_ranks: int = 4,
    nservers: int = 2,
    cfg: Optional[Config] = None,
    timeout: float = 180.0,
) -> GfmcResult:
    expected = {
        "A": num_a,
        "B": num_a * bs_per_a,
        "C": num_a * bs_per_a * cs_per_b,
        "D": num_a * bs_per_a,
    }

    def app(ctx):
        counts = {"A": 0, "B": 0, "C": 0, "D": 0}
        pending_b: dict[int, tuple[int, int]] = {}  # b_id -> (answers left, acc)
        if ctx.rank == 0:
            for a in range(num_a):
                ctx.put(struct.pack("<i", a), TYPE_A, PRIO_A)
            expected_d = expected["D"]
            got_d = 0
            total = 0
            while got_d < expected_d:
                rc, r = ctx.reserve([TYPE_D])
                assert rc == ADLB_SUCCESS, f"master lost D packages: rc={rc}"
                rc, buf = ctx.get_reserved(r.handle)
                (v,) = struct.unpack("<i", buf)
                total += v
                got_d += 1
                counts["D"] += 1
            ctx.set_problem_done()
            return counts, total
        next_b_id = ctx.rank << 20
        while True:
            rc, r = ctx.reserve([TYPE_A, TYPE_B, TYPE_C, TYPE_C_ANSWER])
            if rc != ADLB_SUCCESS:
                return counts, None
            rc, buf = ctx.get_reserved(r.handle)
            if r.work_type == TYPE_A:
                counts["A"] += 1
                (a,) = struct.unpack("<i", buf)
                for b in range(bs_per_a):
                    ctx.put(
                        struct.pack("<ii", a, b), TYPE_B, PRIO_B,
                        answer_rank=ctx.rank,
                    )
            elif r.work_type == TYPE_B:
                counts["B"] += 1
                a, b = struct.unpack("<ii", buf)
                b_id = next_b_id
                next_b_id += 1
                pending_b[b_id] = [cs_per_b, 0]
                for c in range(cs_per_b):
                    # answer must come back to *this* rank, which owns the
                    # pending-B state (the reference's answer_rank pattern)
                    ctx.put(
                        struct.pack("<iii", b_id, a * 100 + b, c),
                        TYPE_C, PRIO_C, answer_rank=ctx.rank,
                    )
            elif r.work_type == TYPE_C:
                counts["C"] += 1
                b_id, ab, c = struct.unpack("<iii", buf)
                value = ab + c  # the "physics"
                ctx.put(
                    struct.pack("<ii", b_id, value),
                    TYPE_C_ANSWER, PRIO_ANSWER,
                    target_rank=r.answer_rank,
                )
            else:  # TYPE_C_ANSWER
                b_id, value = struct.unpack("<ii", buf)
                st = pending_b[b_id]
                st[0] -= 1
                st[1] += value
                if st[0] == 0:
                    del pending_b[b_id]
                    ctx.put(
                        struct.pack("<i", st[1]), TYPE_D, PRIO_ANSWER,
                        target_rank=0,
                    )
                    counts["D"] += 1

    t0 = time.monotonic()
    res = run_world(
        num_app_ranks,
        nservers,
        [TYPE_A, TYPE_B, TYPE_C, TYPE_C_ANSWER, TYPE_D],
        app,
        cfg=cfg or Config(exhaust_check_interval=0.2),
        timeout=timeout,
    )
    elapsed = time.monotonic() - t0
    counts = {"A": 0, "B": 0, "C": 0, "D": 0}
    for rank, (c, _) in res.app_results.items():
        for k, v in c.items():
            counts[k] += v
    # master's D count is receptions; workers' D counts are emissions — count
    # emissions for B/D symmetry
    counts["D"] -= res.app_results[0][0]["D"]
    ok = all(counts[k] == expected[k] for k in ("A", "B", "C", "D"))
    total_tasks = sum(counts.values())
    return GfmcResult(
        ok=ok,
        counts=counts,
        expected=expected,
        elapsed=elapsed,
        tasks_per_sec=total_tasks / elapsed if elapsed > 0 else 0.0,
        tasks_processed=total_tasks,
    )
