"""Batcher: a bag of heterogeneous jobs, dynamically balanced.

Mirrors the reference batcher (reference ``examples/batcher.c``,
``examples/README-batcher.txt``): a master reads a list of independent jobs
of widely varying cost and Puts them untargeted; workers pull and execute.
The reference runs shell commands; here a job is a timed busy/sleep payload,
and the result of interest is elapsed wall-clock vs the serial sum —
the reference's own published example is 9 jobs / 45 s serial finishing in
25 s on 2 workers (``README-batcher.txt:78-95``).
"""

from __future__ import annotations

import dataclasses
import struct
import time
from typing import Optional, Sequence

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

JOB = 1


@dataclasses.dataclass
class BatcherResult:
    elapsed: float
    serial_time: float
    jobs_run: dict[int, int]  # rank -> count
    speedup: float


def run(
    durations: Sequence[float],
    num_app_ranks: int = 3,
    nservers: int = 1,
    cfg: Optional[Config] = None,
    timeout: float = 120.0,
) -> BatcherResult:
    serial = sum(durations)

    def app(ctx):
        n = 0
        if ctx.rank == 0:
            # longest-job-first priorities: classic makespan heuristic the
            # dynamic pool turns into near-optimal schedules
            for d in durations:
                ctx.put(struct.pack("<d", d), JOB, work_prio=int(d * 1000))
        while True:
            rc, r = ctx.reserve([JOB])
            if rc != ADLB_SUCCESS:
                return n
            rc, buf = ctx.get_reserved(r.handle)
            (d,) = struct.unpack("<d", buf)
            time.sleep(d)  # the "shell job"
            n += 1

    t0 = time.monotonic()
    res = run_world(
        num_app_ranks,
        nservers,
        [JOB],
        app,
        cfg=cfg or Config(exhaust_check_interval=0.1),
        timeout=timeout,
    )
    elapsed = time.monotonic() - t0
    return BatcherResult(
        elapsed=elapsed,
        serial_time=serial,
        jobs_run=dict(res.app_results),
        speedup=serial / elapsed if elapsed > 0 else 0.0,
    )
