"""Trickle: steady work arrival at one server, consumers elsewhere —
the dispatch-latency scenario.

Complements :mod:`~adlb_tpu.workloads.hotspot` (bulk placement): here the
producer emits small groups of units at a steady rate roughly matching
aggregate consumption, so the pool never builds a backlog and every unit's
cost is dominated by *discovery* — how fast the balancing layer notices new
work at the hot server and routes it to a parked remote worker. Upstream's
stealing discovers via the periodic qmstat gossip (reference
``src/adlb.c:806-822``: 0.1 s ring interval, plus per-hop staleness), so a
trickling unit waits a gossip period before an RFR can fetch it; the global
planner sees parked requesters and fresh inventory in the same solve and
matches them event-driven.

Metrics: per-unit pop-to-exec latency percentiles (time from Put to
Get_reserved, the coinop methodology over a trickle) and tasks/sec.
"""

from __future__ import annotations

import dataclasses
import struct
import time
from typing import Optional

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

TOKEN = 1
NEVER = 2  # parked-on by hot-server ranks so they never consume locally


@dataclasses.dataclass
class TrickleResult:
    tasks: int
    elapsed: float
    tasks_per_sec: float
    dispatch_p50_ms: float
    dispatch_p90_ms: float


def run(
    n_tasks: int = 200,
    interval: float = 0.01,
    group: int = 2,
    work_time: float = 0.002,
    num_app_ranks: int = 8,
    nservers: int = 4,
    cfg: Optional[Config] = None,
    timeout: float = 300.0,
    consumer: str = "classic",
    stream_depth: int = 4,
) -> TrickleResult:
    """``consumer`` picks the consumer loop shape:

    * ``"classic"`` — the reference's two-call Reserve + Get_reserved loop
      (the continuity baseline);
    * ``"fused"`` — blocking ``get_work`` (one client-visible round trip
      per unit since the remote fused fetch);
    * ``"stream"`` — the pipelined ``get_work_stream(depth=stream_depth)``
      consumer: reserves stay parked across the compute, so a trickling
      unit never waits out a re-park round trip.
    """
    if consumer not in ("classic", "fused", "stream"):
        raise ValueError(f"unknown consumer {consumer!r}")
    base = cfg or Config()
    cfg = dataclasses.replace(
        base,
        put_routing="home",
        exhaust_check_interval=min(base.exhaust_check_interval, 0.2),
    )

    def app(ctx):
        hot_server = ctx.world.home_server(0)
        if ctx.rank == 0:
            # steady trickle into rank 0's home server; the payload carries
            # the put timestamp so consumers can measure put->get latency
            # (CLOCK_MONOTONIC is machine-wide, and this harness is one host)
            n = 0
            while n < n_tasks:
                for _ in range(min(group, n_tasks - n)):
                    ctx.put(struct.pack("<d", time.monotonic()), TOKEN)
                    n += 1
                time.sleep(interval)
            return None
        if ctx.world.home_server(ctx.rank) == hot_server:
            # co-located with the producer: park on a type nobody puts, so
            # every token must be DISCOVERED by a remote server's balancer
            rc, _ = ctx.reserve([NEVER])
            assert rc != ADLB_SUCCESS
            return None
        lats = []
        t0 = time.monotonic()
        t_last = t0
        if consumer == "stream":
            with ctx.get_work_stream([TOKEN], depth=stream_depth) as ws:
                for w in ws:
                    (t_put,) = struct.unpack("<d", w.payload)
                    lats.append(time.monotonic() - t_put)
                    time.sleep(work_time)
                    t_last = time.monotonic()
            return (lats, t0, t_last)
        while True:
            if consumer == "fused":
                rc, w = ctx.get_work([TOKEN])
                if rc != ADLB_SUCCESS:
                    return (lats, t0, t_last)
                buf = w.payload
            else:
                rc, r = ctx.reserve([TOKEN])
                if rc != ADLB_SUCCESS:
                    return (lats, t0, t_last)
                rc, buf = ctx.get_reserved(r.handle)
            (t_put,) = struct.unpack("<d", buf)
            lats.append(time.monotonic() - t_put)
            time.sleep(work_time)
            t_last = time.monotonic()

    res = run_world(num_app_ranks, nservers, [TOKEN, NEVER], app, cfg=cfg,
                    timeout=timeout)
    workers = [v for k, v in res.app_results.items() if k != 0 and v]
    lats = sorted(x for w in workers for x in w[0])
    assert lats, "no tasks consumed"
    t0 = min(w[1] for w in workers)
    t_last = max(w[2] for w in workers)
    span = max(t_last - t0, 1e-9)
    p = lambda q: lats[min(int(q * len(lats)), len(lats) - 1)]  # noqa: E731
    return TrickleResult(
        tasks=len(lats),
        elapsed=span,
        tasks_per_sec=len(lats) / span,
        dispatch_p50_ms=1e3 * p(0.50),
        dispatch_p90_ms=1e3 * p(0.90),
    )
