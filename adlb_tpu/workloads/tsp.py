"""Branch-and-bound TSP: priority-ordered queue stress + targeted broadcast.

Mirrors the reference's design (reference ``examples/tsp.c``): work units are
partial tours with priority favoring longer partials (depth-first flavor);
each worker keeps a local best-so-far bound; improvements are broadcast as
maximum-priority BOUND_UPDT units targeted along a binary tree of app ranks
(reference ``examples/tsp.c:17,189-192``), so bound propagation exercises
targeting and priority preemption together. Terminates by exhaustion once the
tree is pruned dry.
"""

from __future__ import annotations

import dataclasses
import itertools
import random
import struct
import time
from typing import Optional

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

WORK = 1
BOUND_UPDT = 2
BOUND_PRIO = 999999999  # higher than any work priority (reference tsp.c:17)


def make_cities(n: int, seed: int = 0) -> list[tuple[int, int]]:
    rng = random.Random(seed)
    return [(rng.randint(0, 100), rng.randint(0, 100)) for _ in range(n)]


def dist_matrix(cities) -> list[list[int]]:
    def d(a, b):
        return int(
            round(((a[0] - b[0]) ** 2 + (a[1] - b[1]) ** 2) ** 0.5)
        )

    return [[d(a, b) for b in cities] for a in cities]


def brute_force_optimum(dists) -> int:
    """Exact optimum for validation (n small)."""
    n = len(dists)
    best = None
    for perm in itertools.permutations(range(1, n)):
        tour = (0,) + perm
        length = sum(
            dists[tour[i]][tour[(i + 1) % n]] for i in range(n)
        )
        if best is None or length < best:
            best = length
    return best


@dataclasses.dataclass
class TspResult:
    best: int
    tasks_processed: int
    elapsed: float
    tasks_per_sec: float


def run(
    n_cities: int = 9,
    num_app_ranks: int = 4,
    nservers: int = 2,
    seed: int = 0,
    cfg: Optional[Config] = None,
    timeout: float = 180.0,
) -> TspResult:
    cities = make_cities(n_cities, seed)
    dists = dist_matrix(cities)

    def pack(path: list[int], length: int) -> bytes:
        return struct.pack(f"<i{len(path)}i", length, *path)

    def unpack(buf: bytes) -> tuple[int, list[int]]:
        vals = struct.unpack(f"<{len(buf) // 4}i", buf)
        return vals[0], list(vals[1:])

    def greedy_bound() -> int:
        tour, left = [0], set(range(1, n_cities))
        while left:
            nxt = min(left, key=lambda c: dists[tour[-1]][c])
            tour.append(nxt)
            left.remove(nxt)
        return sum(
            dists[tour[i]][tour[(i + 1) % n_cities]] for i in range(n_cities)
        )

    def tree_children(rank: int, nranks: int) -> list[int]:
        return [c for c in (2 * rank + 1, 2 * rank + 2) if c < nranks]

    def app(ctx):
        best = greedy_bound()
        best_known = best
        processed = 0

        def broadcast_bound(val: int) -> None:
            # reference broadcasts improvements down a binary tree of app
            # ranks as max-priority targeted units (tsp.c:189-192)
            for c in tree_children(ctx.rank, ctx.num_app_ranks):
                ctx.put(pack([], val), BOUND_UPDT, BOUND_PRIO, target_rank=c)

        if ctx.rank == 0:
            ctx.put(pack([0], 0), WORK, work_prio=1)
        while True:
            rc, r = ctx.reserve([BOUND_UPDT, WORK])
            if rc != ADLB_SUCCESS:
                return best_known, processed
            rc, buf = ctx.get_reserved(r.handle)
            length, path = unpack(buf)
            if r.work_type == BOUND_UPDT:
                if length < best_known:
                    best_known = length
                    broadcast_bound(length)
                continue
            processed += 1
            if length >= best_known:
                continue  # pruned
            if len(path) == n_cities:
                total = length + dists[path[-1]][0]
                if total < best_known:
                    best_known = total
                    broadcast_bound(total)
                continue
            last = path[-1]
            for city in range(1, n_cities):
                if city in path:
                    continue
                new_len = length + dists[last][city]
                if new_len < best_known:
                    # longer partials get higher priority (tsp.c:239-240)
                    ctx.put(
                        pack(path + [city], new_len), WORK,
                        work_prio=len(path) + 1,
                    )

    t0 = time.monotonic()
    res = run_world(
        num_app_ranks,
        nservers,
        [WORK, BOUND_UPDT],
        app,
        cfg=cfg or Config(exhaust_check_interval=0.15),
        timeout=timeout,
    )
    elapsed = time.monotonic() - t0
    best = min(v[0] for v in res.app_results.values())
    tasks = sum(v[1] for v in res.app_results.values())
    return TspResult(
        best=best,
        tasks_processed=tasks,
        elapsed=elapsed,
        tasks_per_sec=tasks / elapsed if elapsed > 0 else 0.0,
    )
