"""c3 — batch-generation GFMC variant with answer economy over the pool.

Mirrors the reference ``examples/c3.c``: a small subset of slaves
(``num_app_ranks/20``, at least 1 — reference ``examples/c3.c:106-108``)
runs a two-level generation loop: per (loop1, loop2) a *batch* of A units is
Put, then the generator reserves ``[TYPE_A, TYPE_A_ANSWER]`` until every A
of the batch is answered — executing As itself and counting directly when
``answer_rank`` is itself, else shipping a **targeted** TYPE_A_ANSWER unit
back through the pool (reference ``examples/c3.c:196-249``). Per loop1 it
then Puts a batch of Bs. All slaves join the wildcard phase-2 loop: an A is
executed and answered with a targeted A_ANSWER; a B fans out a batch of Cs
and gathers ``[TYPE_C, TYPE_C_ANSWER]`` (C answers always travel as
targeted C_ANSWER units, even to self — reference ``examples/c3.c:391-404``);
a wildcard C is executed and answered likewise. The master parks on
``TYPE_NEVER_PUT_FOR_MASTER`` so only exhaustion releases it (reference
``examples/c3.c:151-166``) — the whole run terminates **by exhaustion**.

Self-check (reference ``examples/c3.c:458-463``): summed A answers ==
``n1 * loop1 * loop2 * nas`` and summed C answers == ``n1 * loop1 * nbs *
ncs``.
"""

from __future__ import annotations

import dataclasses
import math
import struct
import time
from typing import Optional

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

TYPE_A = 1
TYPE_A_ANSWER = 2
TYPE_B = 3
TYPE_C = 4
TYPE_C_ANSWER = 5
TYPE_NEVER_PUT_FOR_MASTER = 6

PRIO_A, PRIO_B, PRIO_C = 3, 2, 1
PRIO_ANSWER = 9

_U = struct.Struct("<iii")  # (orig_rank, uid, cidx)


def _fake_work(secs: float) -> None:
    t0 = time.perf_counter()
    v = 99.99
    while time.perf_counter() - t0 < secs:
        v = math.sqrt(v + 50000.0) + 1.0


@dataclasses.dataclass
class C3Result:
    a_answers: int
    c_answers: int
    exp_as: int
    exp_cs: int
    ok: bool


def run(
    nas: int = 6,
    nbs: int = 3,
    ncs: int = 4,
    loop1: int = 2,
    loop2: int = 2,
    atime: float = 0.002,
    ctime: float = 0.001,
    num_app_ranks: int = 4,
    nservers: int = 1,
    cfg: Optional[Config] = None,
    timeout: float = 180.0,
) -> C3Result:
    if num_app_ranks < 2:
        raise ValueError("c3 needs a master and at least one slave")
    n1 = max(num_app_ranks // 20, 1)  # slaves doing the generation phase
    exp_as = n1 * loop1 * loop2 * nas
    exp_bs = n1 * loop1 * nbs
    exp_cs = exp_bs * ncs

    def master(ctx):
        rc, _ = ctx.reserve([TYPE_NEVER_PUT_FOR_MASTER])
        assert rc != ADLB_SUCCESS  # only exhaustion/NMW releases the master
        return (0, 0)

    def handle_c_gather(ctx, n_expected: int):
        """Reserve [C, C_ANSWER] until n_expected answers (c3.c:355-419)."""
        n = 0
        while n < n_expected:
            rc, r = ctx.reserve([TYPE_C, TYPE_C_ANSWER])
            if rc != ADLB_SUCCESS:
                return n, rc
            rc2, buf = ctx.get_reserved(r.handle)
            if rc2 != ADLB_SUCCESS:
                return n, rc2
            if r.work_type == TYPE_C:
                _fake_work(ctime)
                ctx.put(buf, TYPE_C_ANSWER, work_prio=PRIO_ANSWER,
                        target_rank=r.answer_rank)
            else:
                n += 1
        return n, ADLB_SUCCESS

    def slave(ctx):
        a_answers = 0
        c_answers = 0
        num_as = num_bs = 0
        if 1 <= ctx.rank <= n1:  # generation phase
            for _l1 in range(loop1):
                for _l2 in range(loop2):
                    ctx.begin_batch_put(b"")
                    for _ in range(nas):
                        num_as += 1
                        ctx.put(_U.pack(ctx.rank, num_as, 0), TYPE_A,
                                work_prio=PRIO_A, answer_rank=ctx.rank)
                    ctx.end_batch_put()
                    got = 0
                    while got < nas:
                        rc, r = ctx.reserve([TYPE_A, TYPE_A_ANSWER])
                        assert rc == ADLB_SUCCESS, (
                            "exhaustion before all A answers")
                        rc2, buf = ctx.get_reserved(r.handle)
                        assert rc2 == ADLB_SUCCESS
                        if r.work_type == TYPE_A:
                            _fake_work(atime)
                            if r.answer_rank == ctx.rank:
                                got += 1
                                a_answers += 1
                            else:
                                ctx.put(buf, TYPE_A_ANSWER,
                                        work_prio=PRIO_ANSWER,
                                        target_rank=r.answer_rank)
                        else:
                            got += 1
                            a_answers += 1
                ctx.begin_batch_put(b"")
                for _ in range(nbs):
                    num_bs += 1
                    ctx.put(_U.pack(ctx.rank, num_bs, 0), TYPE_B,
                            work_prio=PRIO_B, answer_rank=ctx.rank)
                ctx.end_batch_put()
        # phase 2: everyone drains the pool until exhaustion
        while True:
            rc, r = ctx.reserve()
            if rc != ADLB_SUCCESS:
                break
            rc2, buf = ctx.get_reserved(r.handle)
            if rc2 != ADLB_SUCCESS:
                break
            if r.work_type == TYPE_A:
                _fake_work(atime)
                ctx.put(buf, TYPE_A_ANSWER, work_prio=PRIO_ANSWER,
                        target_rank=r.answer_rank)
            elif r.work_type == TYPE_A_ANSWER:
                a_answers += 1
            elif r.work_type == TYPE_B:
                orig, uid, _ = _U.unpack(buf)
                ctx.begin_batch_put(b"")
                for i in range(ncs):
                    ctx.put(_U.pack(orig, uid, i), TYPE_C,
                            work_prio=PRIO_C, answer_rank=ctx.rank)
                ctx.end_batch_put()
                got, rc = handle_c_gather(ctx, ncs)
                c_answers += got
                if rc != ADLB_SUCCESS:
                    break
            elif r.work_type == TYPE_C:
                _fake_work(ctime)
                ctx.put(buf, TYPE_C_ANSWER, work_prio=PRIO_ANSWER,
                        target_rank=r.answer_rank)
            elif r.work_type == TYPE_C_ANSWER:
                c_answers += 1
        return (a_answers, c_answers)

    def app(ctx):
        return master(ctx) if ctx.rank == 0 else slave(ctx)

    res = run_world(
        num_app_ranks,
        nservers,
        [TYPE_A, TYPE_A_ANSWER, TYPE_B, TYPE_C, TYPE_C_ANSWER,
         TYPE_NEVER_PUT_FOR_MASTER],
        app,
        cfg=cfg or Config(exhaust_check_interval=0.25),
        timeout=timeout,
    )
    a_total = sum(a for a, _ in res.app_results.values())
    c_total = sum(c for _, c in res.app_results.values())
    return C3Result(
        a_answers=a_total,
        c_answers=c_total,
        exp_as=exp_as,
        exp_cs=exp_cs,
        ok=a_total == exp_as and c_total == exp_cs,
    )
