"""add2 — the answer-economy smoke workload.

Mirrors the reference ``examples/add2.c``: rank 0 Puts TYPE_AB units each
holding two integers; workers Reserve them, add the pair, and Put the sum
back as a TYPE_C unit targeted at rank 0 (the answer_rank economy); rank 0
collects every sum and verifies the total against the locally computed
expectation — a self-checking test of Put/Reserve/targeting/termination.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional, Sequence

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

TYPE_AB = 1
TYPE_C = 2


@dataclasses.dataclass
class Add2Result:
    total: int
    expected: int
    ok: bool
    sums_by_rank: dict[int, int]  # rank -> pairs added


def run(
    pairs: Sequence[tuple[int, int]],
    num_app_ranks: int = 3,
    nservers: int = 1,
    cfg: Optional[Config] = None,
    timeout: float = 120.0,
) -> Add2Result:
    if num_app_ranks < 2:
        # rank 0 only collects TYPE_C answers; someone else must serve
        # TYPE_AB or the exhaustion vote flushes rank 0's reserve
        raise ValueError("add2 needs at least 2 app ranks (1 master + workers)")
    expected = sum(a + b for a, b in pairs)
    out: dict = {}

    def app(ctx):
        added = 0
        if ctx.rank == 0:
            for a, b in pairs:
                ctx.put(struct.pack("<qq", a, b), TYPE_AB, answer_rank=0)
            total = 0
            for _ in range(len(pairs)):
                rc, r = ctx.reserve([TYPE_C])
                assert rc == ADLB_SUCCESS
                rc, buf = ctx.get_reserved(r.handle)
                (s,) = struct.unpack("<q", buf)
                total += s
            out["total"] = total
            ctx.set_problem_done()
            return added
        while True:
            rc, r = ctx.reserve([TYPE_AB])
            if rc != ADLB_SUCCESS:
                return added
            rc, buf = ctx.get_reserved(r.handle)
            a, b = struct.unpack("<qq", buf)
            ctx.put(struct.pack("<q", a + b), TYPE_C, target_rank=r.answer_rank)
            added += 1

    res = run_world(
        num_app_ranks,
        nservers,
        [TYPE_AB, TYPE_C],
        app,
        cfg=cfg or Config(exhaust_check_interval=0.25),
        timeout=timeout,
    )
    total = out["total"]
    return Add2Result(
        total=total,
        expected=expected,
        ok=total == expected,
        sums_by_rank=dict(res.app_results),
    )
