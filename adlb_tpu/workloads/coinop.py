"""coinop: the pop-latency microbenchmark.

Mirrors the fork's addition (reference ``examples/coinop.cpp:79-126,190-213``):
one producer floods N tokens through the pool; every worker accumulates the
latency of each Reserve+Get pop in a streaming :class:`RunningStats` (the
reference's stats.c accumulator pattern) and reports mean/stddev (gathered
to the producer in the reference via MPI_Gather; here returned through app
results, along with the raw latencies for driver-side percentiles).
This is the steal-to-exec latency probe used by BASELINE.md.
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Optional

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS
from adlb_tpu.utils import RunningStats

TOKEN = 1


@dataclasses.dataclass
class CoinopResult:
    pops: int
    latency_mean_ms: float
    latency_p50_ms: float
    latency_p95_ms: float
    per_worker: dict[int, tuple[float, float]]  # rank -> (mean ms, stddev ms)
    elapsed: float
    pops_per_sec: float


def run(
    n_tokens: int = 500,
    num_app_ranks: int = 4,
    nservers: int = 2,
    token_bytes: int = 64,
    work_time: float = 0.0,
    cfg: Optional[Config] = None,
    timeout: float = 180.0,
    spawn: bool = False,
    consumer: str = "classic",
) -> CoinopResult:
    """``spawn=True`` runs real processes over spawn_world — the shape
    that exercises the process fabrics (``Config(fabric)``: shm rings vs
    TCP); the default in-proc thread world measures the queue fabric.
    ``consumer="batch:N"`` pops through the batched fused get_work
    (per-pop latency amortizes the round trip over the batch — the
    framework's own best consumer path, as in the native bench rows);
    "classic" keeps the reference's two-call Reserve+Get loop."""
    payload = b"c" * token_bytes
    batch = int(consumer.split(":")[1]) if consumer.startswith("batch") \
        else 0

    def app(ctx):
        if ctx.rank == 0:
            for i in range(n_tokens):
                ctx.put(payload, TOKEN, work_prio=0)
            # producer finalizes immediately; workers drain the pool and the
            # exhaustion protocol ends the world once it runs dry
            return [], 0.0, 0.0
        lats = []
        stats = RunningStats(f"pop-latency-rank{ctx.rank}")
        stats.on()
        if batch > 0:
            while True:
                t0 = time.monotonic()
                rc, units = ctx.get_work_batch([TOKEN], max_units=batch)
                if rc != ADLB_SUCCESS or not units:
                    return lats, stats.mean, stats.stddev
                dt = (time.monotonic() - t0) / len(units)
                for _ in units:
                    lats.append(dt)
                    stats.enter(dt)
                    if work_time > 0:
                        time.sleep(work_time)
        while True:
            t0 = time.monotonic()
            rc, r = ctx.reserve([TOKEN])
            if rc != ADLB_SUCCESS:
                return lats, stats.mean, stats.stddev
            rc, buf, _tq = ctx.get_reserved_timed(r.handle)
            dt = time.monotonic() - t0
            lats.append(dt)
            stats.enter(dt)
            if work_time > 0:
                time.sleep(work_time)

    t0 = time.monotonic()
    if spawn:
        from adlb_tpu.runtime.transport_tcp import spawn_world

        res = spawn_world(
            num_app_ranks,
            nservers,
            [TOKEN],
            app,
            cfg=cfg or Config(exhaust_check_interval=0.25),
            timeout=timeout,
        )
    else:
        res = run_world(
            num_app_ranks,
            nservers,
            [TOKEN],
            app,
            cfg=cfg or Config(exhaust_check_interval=0.25),
            timeout=timeout,
        )
    elapsed = time.monotonic() - t0
    all_lats = sorted(
        lat for rank, (lats, _m, _s) in res.app_results.items()
        for lat in lats
    )
    per_worker = {
        rank: (mean * 1e3, stddev * 1e3)
        for rank, (lats, mean, stddev) in res.app_results.items()
        if rank != 0 and lats
    }
    n = len(all_lats)
    return CoinopResult(
        pops=n,
        latency_mean_ms=(statistics.mean(all_lats) * 1e3) if n else 0.0,
        latency_p50_ms=(all_lats[n // 2] * 1e3) if n else 0.0,
        latency_p95_ms=(all_lats[int(n * 0.95)] * 1e3) if n else 0.0,
        per_worker=per_worker,
        elapsed=elapsed,
        pops_per_sec=n / elapsed if elapsed > 0 else 0.0,
    )
