"""Branch-and-bound TSP on the all-native plane: C clients
(``examples/tsp_c.c``) against the C++ server daemons, with the JAX
balancer sidecar planning in tpu mode — the reference's priority-queue
stress (reference ``examples/tsp.c``) at OS-process scale.

The harness generates the city matrix (one source of truth, shared with
the in-proc port in :mod:`adlb_tpu.workloads.tsp`) and hands it to the C
clients via ``ADLB_TSP_DISTS``; ``min(best)`` across ranks is validated
against the brute-force optimum when ``n_cities`` is small enough.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from adlb_tpu.runtime.world import Config
from adlb_tpu.workloads.tsp import brute_force_optimum, dist_matrix, make_cities


@dataclasses.dataclass
class TspNativeResult:
    best: int
    optimum: Optional[int]  # brute-forced when n_cities <= 10, else None
    tasks: int  # WORK units processed across ranks (expansions + prunes)
    elapsed: float
    tasks_per_sec: float
    wait_pct: float  # mean fraction of makespan blocked acquiring work


def run(
    n_cities: int = 9,
    num_app_ranks: int = 4,
    nservers: int = 2,
    seed: int = 0,
    cfg: Optional[Config] = None,
    timeout: float = 300.0,
    fetch: str = "single",
) -> TspNativeResult:
    """``fetch="batch"`` / ``"batch:<k>"`` switches the C clients to the
    batched fused fetch (``ADLB_Get_work_batch``); priority order inside
    a batch keeps BOUND_UPDT units ahead of WORK."""
    from adlb_tpu.native.capi import run_native_probe

    dists = dist_matrix(make_cities(n_cities, seed))
    flat = ",".join(str(d) for row in dists for d in row)
    env = {
        "ADLB_TSP_N": str(n_cities),
        "ADLB_TSP_DISTS": flat,
    }
    if fetch != "single":
        env["ADLB_TSP_FETCH"] = fetch
    results = run_native_probe(
        "tsp_c.c",
        types=[1, 2],
        env_extra=env,
        num_app_ranks=num_app_ranks,
        nservers=nservers,
        cfg=cfg,
        timeout=timeout,
    )
    from adlb_tpu.native.capi import (
        check_fetch_mode,
        parse_probe_lines,
        probe_aggregate,
    )

    rows = parse_probe_lines(results, "TSP")
    check_fetch_mode(rows, fetch, "tsp")
    tasks, elapsed, rate, wait_pct = probe_aggregate(rows)
    return TspNativeResult(
        best=min(r["best"] for r in rows),
        optimum=brute_force_optimum(dists) if n_cities <= 10 else None,
        tasks=tasks,
        elapsed=elapsed,
        tasks_per_sec=rate,
        wait_pct=wait_pct,
    )
