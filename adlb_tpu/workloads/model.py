"""model — the minimal master/worker dummy-work model.

Mirrors the reference ``examples/model.c``: the master app rank Puts
``numprobs`` untargeted PROBLEM units at a fixed priority; every app rank
(master included) then loops a wildcard Reserve (``req_types[0] = -1``,
reference ``examples/model.c:90-92``), performs a fixed chunk of dummy work
per unit (the reference sleeps 1 s, ``examples/model.c:113``), and counts
units until the run terminates **by exhaustion** — model.c never calls
Set_problem_done, so it exercises the double-pass exhaustion vote end to end
(reference ``src/adlb.c:1575-1650``).

Self-check: the per-rank counts must sum to ``numprobs``.
"""

from __future__ import annotations

import dataclasses
import struct
import time
from typing import Optional

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

PROBLEM = 1
SOLUTION = 2  # declared by the reference but never Put; kept for parity
PROBLEM_PRIORITY = 5


@dataclasses.dataclass
class ModelResult:
    num_done: int
    numprobs: int
    ok: bool
    done_by_rank: dict[int, int]
    elapsed: float


def run(
    numprobs: int = 20,
    work_secs: float = 0.01,
    num_app_ranks: int = 4,
    nservers: int = 1,
    cfg: Optional[Config] = None,
    timeout: float = 120.0,
) -> ModelResult:
    t0 = time.monotonic()

    def app(ctx):
        if ctx.rank == 0:
            for i in range(numprobs):
                rc = ctx.put(
                    struct.pack("<i", i), PROBLEM, work_prio=PROBLEM_PRIORITY
                )
                assert rc == ADLB_SUCCESS
        num_done = 0
        while True:
            rc, r = ctx.reserve()  # wildcard, like req_types[0] = -1
            if rc != ADLB_SUCCESS:
                break  # NO_MORE_WORK / DONE_BY_EXHAUSTION
            assert r.work_type == PROBLEM, f"unexpected type {r.work_type}"
            rc, buf = ctx.get_reserved(r.handle)
            if rc != ADLB_SUCCESS:
                break
            time.sleep(work_secs)  # dummy work (model.c sleeps 1 s)
            num_done += 1
        return num_done

    res = run_world(
        num_app_ranks,
        nservers,
        [PROBLEM, SOLUTION],
        app,
        cfg=cfg or Config(exhaust_check_interval=0.25),
        timeout=timeout,
    )
    done_by_rank = dict(res.app_results)
    total = sum(done_by_rank.values())
    return ModelResult(
        num_done=total,
        numprobs=numprobs,
        ok=total == numprobs,
        done_by_rank=done_by_rank,
        elapsed=time.monotonic() - t0,
    )
