"""n-queens on the all-native plane: C clients (``examples/nq_c.c``)
against the C++ server daemons — the BASELINE.json north-star workload
(reference ``examples/nq.c``) at OS-process scale, with the same
machine-readable per-rank metrics as the other native probes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from adlb_tpu.runtime.world import Config
from adlb_tpu.workloads.nq import KNOWN_SOLUTIONS


@dataclasses.dataclass
class NqNativeResult:
    solutions: int
    expected: Optional[int]  # known answer when tabulated, else None
    tasks: int  # work units processed across ranks
    elapsed: float
    tasks_per_sec: float
    wait_pct: float  # mean fraction of makespan blocked acquiring work


def run(
    n: int = 7,
    cutoff: int = 2,
    num_app_ranks: int = 4,
    nservers: int = 2,
    cfg: Optional[Config] = None,
    timeout: float = 300.0,
) -> NqNativeResult:
    from adlb_tpu.native.capi import run_native_probe

    results = run_native_probe(
        "nq_c.c",
        types=[1],
        env_extra={
            "ADLB_NQ_N": str(n),
            "ADLB_NQ_CUTOFF": str(cutoff),
        },
        num_app_ranks=num_app_ranks,
        nservers=nservers,
        cfg=cfg,
        timeout=timeout,
    )
    from adlb_tpu.native.capi import parse_probe_lines, probe_aggregate

    rows = parse_probe_lines(results, "NQ")
    tasks, elapsed, rate, wait_pct = probe_aggregate(rows)
    return NqNativeResult(
        solutions=sum(r["solutions"] for r in rows),
        expected=KNOWN_SOLUTIONS.get(n),
        tasks=tasks,
        elapsed=elapsed,
        tasks_per_sec=rate,
        wait_pct=wait_pct,
    )
