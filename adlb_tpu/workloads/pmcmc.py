"""Embarrassingly-parallel Markov Chain Monte Carlo (hard-disk problem).

Mirrors the reference's pmcmc demo (reference ``examples/pmcmc.c``): the
master rank Puts integer RNG seeds as WORK units; each worker pulls a seed,
runs a Metropolis chain proposing random moves of four hard disks in the
unit box (a move is accepted if the disk stays inside the ``sigma`` margin
and clears every other disk), and Puts the final disk positions back as a
SOLN unit *targeted* at the master (reference ``examples/pmcmc.c:208``,
``target_rank=0``). The master Reserves exactly one SOLN per seed with a
type-filtered reserve, then declares the problem done.

Validation: solutions are seed-deterministic, every returned configuration
must respect the margin and the pairwise separation invariant, and the
master must collect exactly ``num_mcs`` solutions.
"""

from __future__ import annotations

import dataclasses
import struct
import time
from typing import Optional

import numpy as np

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

WORK, SOLN = 1, 2

NUMDISKS = 4
SIGMA = 0.20
DELTA = 0.15
_SEP = SIGMA * SIGMA  # the reference compares distance against sigma^2


def chain(seed: int, steps: int) -> np.ndarray:
    """Run one Metropolis chain; returns the final [NUMDISKS, 2] positions.

    Same model as the reference's worker body (``examples/pmcmc.c:155-205``):
    start from the 4-disk lattice, propose uniform moves in
    ``[-DELTA, DELTA]^2`` for a random disk, accept iff inside the margin
    and at least ``SIGMA**2`` from every other disk. Proposals are drawn in
    one vectorized batch; the accept/update loop is inherently sequential.
    """
    rng = np.random.default_rng(seed)
    pts = np.array(
        [[0.25, 0.25], [0.75, 0.25], [0.25, 0.75], [0.75, 0.75]], dtype=np.float64
    )
    choices = rng.integers(0, NUMDISKS, size=steps)
    moves = rng.uniform(-DELTA, DELTA, size=(steps, 2))
    lo, hi = SIGMA, 1.0 - SIGMA
    for k in range(steps):
        c = choices[k]
        b = pts[c] + moves[k]
        if b[0] < lo or b[0] > hi or b[1] < lo or b[1] > hi:
            continue
        d = pts - b
        dist = np.sqrt(np.einsum("ij,ij->i", d, d))
        dist[c] = np.inf
        if (dist >= _SEP).all():
            pts[c] = b
    return pts


def valid_config(pts: np.ndarray) -> bool:
    lo, hi = SIGMA, 1.0 - SIGMA
    if (pts < lo).any() or (pts > hi).any():
        return False
    for i in range(NUMDISKS):
        for j in range(i + 1, NUMDISKS):
            if float(np.linalg.norm(pts[i] - pts[j])) < _SEP:
                return False
    return True


@dataclasses.dataclass
class PmcmcResult:
    ok: bool
    solutions: dict[int, np.ndarray]  # seed -> final positions
    elapsed: float
    chains_per_sec: float


def run(
    num_mcs: int = 8,
    steps: int = 4000,
    num_app_ranks: int = 4,
    nservers: int = 1,
    cfg: Optional[Config] = None,
    timeout: float = 120.0,
) -> PmcmcResult:
    fmt_soln = f"<i{NUMDISKS * 2}d"

    def app(ctx):
        if ctx.rank == 0:
            for i in range(num_mcs):
                ctx.put(struct.pack("<i", i + 100), WORK, work_prio=1)
            solutions: dict[int, np.ndarray] = {}
            for _ in range(num_mcs):
                rc, r = ctx.reserve([SOLN])
                assert rc == ADLB_SUCCESS and r.work_type == SOLN, (
                    f"master reserve failed rc={rc}"
                )
                rc, buf = ctx.get_reserved(r.handle)
                vals = struct.unpack(fmt_soln, buf)
                solutions[vals[0]] = np.array(vals[1:]).reshape(NUMDISKS, 2)
            ctx.set_problem_done()
            return solutions
        while True:
            rc, r = ctx.reserve([WORK])
            if rc != ADLB_SUCCESS:
                return {}
            rc, buf = ctx.get_reserved(r.handle)
            (seed,) = struct.unpack("<i", buf)
            pts = chain(seed, steps)
            ctx.put(
                struct.pack(fmt_soln, seed, *pts.ravel().tolist()),
                SOLN,
                work_prio=2,
                target_rank=0,
            )

    t0 = time.monotonic()
    res = run_world(
        num_app_ranks,
        nservers,
        [WORK, SOLN],
        app,
        cfg=cfg or Config(exhaust_check_interval=0.2),
        timeout=timeout,
    )
    elapsed = time.monotonic() - t0
    solutions = res.app_results[0]
    ok = len(solutions) == num_mcs and all(
        valid_config(p) for p in solutions.values()
    )
    return PmcmcResult(
        ok=ok,
        solutions=solutions,
        elapsed=elapsed,
        chains_per_sec=num_mcs / elapsed if elapsed > 0 else 0.0,
    )
