"""c1 — the GFMC-precursor epoch workload with app-level answer messages.

Mirrors the reference ``examples/c1.c``: each slave seeds its share of A
units; an A advances through ``nunits`` time units (re-Put with decaying
priority, reference ``examples/c1.c:186-194``), spawning a B every
``A_EPOCH`` units; a B fans out ``CS_PER_B`` C units (batch put) and then
*gathers* exactly CS_PER_B C-answers — executing pool Cs itself via
non-blocking Ireserve while polling for answers, the reference's
compute/communicate overlap idiom (``examples/c1.c:212-263``). C answers
travel **outside the pool**, as point-to-point messages on app_comm
(``MPI_Send(TAG_C_ANSWER)``, ``examples/c1.c:247,296``) — exercising this
framework's app-messaging layer — and each completed B reports its sum to
the master the same way (``TAG_B_ANSWER``, ``examples/c1.c:267``). The
master counts ``num_As * (nunits // A_EPOCH)`` B answers, then calls
Set_problem_done.

Self-check: master's accumulated sum == num_As * (nunits // A_EPOCH) *
CS_PER_B (reference ``examples/c1.c:116-118``).
"""

from __future__ import annotations

import dataclasses
import math
import struct
from typing import Optional

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_NO_CURRENT_WORK, ADLB_SUCCESS

A_EPOCH = 2  # reference examples/c1.c:10
CS_PER_B = 4  # reference examples/c1.c:11

TAG_B_ANSWER = 1
TAG_C_ANSWER = 2

TYPE_A = 1
TYPE_B = 2
TYPE_C = 3

_A = struct.Struct("<iii")  # (orig_rank, aid, time_unit)
_BC = struct.Struct("<ii")  # (orig_rank, aid)


def _delay(reps: int) -> float:
    v = 2.0
    for _ in range(reps):
        v = math.sqrt(v + 5000000.0) + 1
    return v


@dataclasses.dataclass
class C1Result:
    total: int
    expected: int
    ok: bool


def run(
    num_as: int = 4,
    nunits: int = A_EPOCH * 2,
    num_app_ranks: int = 4,
    nservers: int = 1,
    delay_reps: int = 2000,
    cfg: Optional[Config] = None,
    timeout: float = 120.0,
) -> C1Result:
    if num_app_ranks < 2:
        raise ValueError("c1 needs a master and at least one slave")
    num_bs = num_as * (nunits // A_EPOCH)
    expected = num_bs * CS_PER_B
    out: dict = {}

    def master(ctx):
        total = 0
        for _ in range(num_bs):
            payload, _src, tag = ctx.app_recv(apptag=TAG_B_ANSWER)
            assert tag == TAG_B_ANSWER
            total += payload
        ctx.set_problem_done()
        out["total"] = total
        return total

    def gather_c_answers(ctx):
        """B-handler: execute pool Cs while polling for C answers
        (examples/c1.c:212-263)."""
        acc = 0
        n = 0
        while n < CS_PER_B:
            if ctx.app_iprobe(apptag=TAG_C_ANSWER):
                payload, _src, _tag = ctx.app_recv(apptag=TAG_C_ANSWER)
                acc += payload
                n += 1
                continue
            rc, r = ctx.ireserve([TYPE_C])
            if rc == ADLB_SUCCESS:
                rc2, buf = ctx.get_reserved(r.handle)
                if rc2 != ADLB_SUCCESS:
                    return acc, n, rc2
                _delay(delay_reps)
                if r.answer_rank == ctx.rank:
                    acc += 1
                    n += 1
                else:
                    ctx.app_send(r.answer_rank, 1, apptag=TAG_C_ANSWER)
            elif rc == ADLB_NO_CURRENT_WORK:
                # the reference blocks in MPI_Recv here; a bounded wait +
                # re-probe is the hang-proof equivalent
                got = ctx.app_recv(apptag=TAG_C_ANSWER, timeout=0.05)
                if got is not None:
                    acc += got[0]
                    n += 1
            else:
                return acc, n, rc  # NO_MORE_WORK etc.
        return acc, n, ADLB_SUCCESS

    def slave(ctx):
        slaves = num_app_ranks - 1
        base, extra = divmod(num_as, slaves)
        mine = base + (1 if ctx.rank <= extra else 0)
        prio_a = 0
        ctx.begin_batch_put(b"")
        for i in range(mine):
            ctx.put(
                _A.pack(ctx.rank, i + 1, 1),
                TYPE_A,
                work_prio=prio_a,
                answer_rank=ctx.rank,
            )
        ctx.end_batch_put()
        while True:
            rc, r = ctx.reserve()
            if rc != ADLB_SUCCESS:
                return
            if r.work_type == TYPE_A:
                rc, buf = ctx.get_reserved(r.handle)
                if rc != ADLB_SUCCESS:
                    return
                orig, aid, t = _A.unpack(buf)
                _delay(delay_reps)
                if t % A_EPOCH == 0 and t <= nunits:
                    ctx.put(
                        _BC.pack(orig, aid),
                        TYPE_B,
                        work_prio=r.work_prio - 2,
                        answer_rank=ctx.rank,
                    )
                if t < nunits:
                    ctx.put(
                        _A.pack(orig, aid, t + 1),
                        TYPE_A,
                        work_prio=r.work_prio - 3,
                        answer_rank=ctx.rank,
                    )
            elif r.work_type == TYPE_B:
                rc, buf = ctx.get_reserved(r.handle)
                if rc != ADLB_SUCCESS:
                    return
                ctx.begin_batch_put(b"")
                for _ in range(CS_PER_B):
                    ctx.put(
                        buf, TYPE_C, work_prio=r.work_prio + 1,
                        answer_rank=ctx.rank,
                    )
                ctx.end_batch_put()
                acc, _n, rc = gather_c_answers(ctx)
                if rc != ADLB_SUCCESS:
                    return
                ctx.app_send(0, acc, apptag=TAG_B_ANSWER)
            elif r.work_type == TYPE_C:
                rc, buf = ctx.get_reserved(r.handle)
                if rc != ADLB_SUCCESS:
                    return
                _delay(delay_reps)
                # wildcard-reserved C: answer goes back to the B's owner
                # (examples/c1.c:289-297; the self case cannot arise here,
                # the owner only consumes own Cs through gather's Ireserve)
                if r.answer_rank != ctx.rank:
                    ctx.app_send(r.answer_rank, 1, apptag=TAG_C_ANSWER)

    def app(ctx):
        return master(ctx) if ctx.rank == 0 else slave(ctx)

    run_world(
        num_app_ranks,
        nservers,
        [TYPE_A, TYPE_B, TYPE_C],
        app,
        cfg=cfg or Config(),
        timeout=timeout,
    )
    total = out.get("total", -1)
    return C1Result(total=total, expected=expected, ok=total == expected)
