"""Hotspot on the all-native plane: C clients + C++ server daemons (+ JAX
balancer sidecar in tpu mode), every rank its own OS process.

This is the scale story the in-process harness cannot tell: one Python
interpreter caps a threaded world at ~5k messages/s (GIL), while the
native plane runs the entire data path in C/C++ processes — the Python
runtime appears only as the balancer brain. Scenario shape and metrics
match :mod:`adlb_tpu.workloads.hotspot` (all work enters one server via
home routing, consumers spread everywhere; reference analogue: the
skel.c synthetic stress, reference ``examples/skel.c:10-40``).
"""

from __future__ import annotations

from typing import Optional

from adlb_tpu.runtime.world import Config
from adlb_tpu.workloads.hotspot import HotspotResult


def run(
    n_tasks: int = 2000,
    work_us: int = 2000,
    num_app_ranks: int = 32,
    nservers: int = 8,
    cfg: Optional[Config] = None,
    timeout: float = 300.0,
    fetch: str = "single",
) -> HotspotResult:
    """``fetch="batch"`` (or ``"batch:<k>"``) switches the consumers to
    the batched fused fetch ``ADLB_Get_work_batch`` so the bench can
    measure the single-vs-batch delta on this plane."""
    from adlb_tpu.native.capi import run_native_probe

    env = {
        "ADLB_PUT_ROUTING": "home",
        "ADLB_HOT_NTASKS": str(n_tasks),
        "ADLB_HOT_WORK_US": str(work_us),
    }
    if fetch != "single":
        env["ADLB_HOT_FETCH"] = fetch
    results = run_native_probe(
        "hotspot_c.c",
        types=[1],
        env_extra=env,
        num_app_ranks=num_app_ranks,
        nservers=nservers,
        cfg=cfg,
        timeout=timeout,
    )
    from adlb_tpu.native.capi import check_fetch_mode, parse_probe_lines

    raw = parse_probe_lines(results, "HOT")
    check_fetch_mode(raw, fetch, "hotspot", skip_first=True)
    rows = [
        (r["done"], r["busy"], r["t0"], r["t1"], r.get("wait", 0.0))
        for r in raw
    ]
    workers = rows[1:]
    tasks = sum(r[0] for r in workers)
    # rank 0 is a pure producer: the makespan starts at its first put but
    # must END at the last WORKER's finish, so probe_makespan (which maxes
    # over all rows) is deliberately not used here
    t_begin = min(r[2] for r in rows)
    t_end = max(r[3] for r in workers)
    elapsed = max(t_end - t_begin, 1e-9)
    # busy is NOMINAL compute (done x work_us, computed by the C worker):
    # utilization = useful worker-seconds / available worker-seconds. A
    # wall-clock busy measure would count involuntary scheduler delay
    # inside the compute sleep as "busy", inflating utilization exactly
    # in the runs where the oversubscribed kernel scheduler is the
    # bottleneck (the round-2 64-rank idle-vs-throughput contradiction).
    busy = (
        sum(r[1] / elapsed for r in workers) / len(workers) if workers else 0.0
    )
    wait = (
        sum(r[4] / elapsed for r in workers) / len(workers) if workers else 0.0
    )
    return HotspotResult(
        tasks=tasks,
        elapsed=elapsed,
        tasks_per_sec=tasks / elapsed,
        busy_fraction=busy,
        idle_pct=100.0 * (1.0 - busy),
        wait_pct=100.0 * wait,
    )
