"""skel — the configurable synthetic stress probe.

Mirrors the reference ``examples/skel.c`` + ``examples/c2.c``: a fixed
palette of synthetic work types, each with its own payload size, priority
band, and simulated execution delay (reference ``examples/skel.c:10-40``).
Rank 0 floods the pool with a configurable mix; every rank consumes any
type, sleeps the type's delay, and tallies per-type counts. The run is
self-checking: consumed-per-type must equal produced-per-type (the c4-style
work-unit accounting, reference ``examples/c4.c:495-502``).
"""

from __future__ import annotations

import dataclasses
import struct
import time
from typing import Optional, Sequence

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS


@dataclasses.dataclass(frozen=True)
class TypeSpec:
    """One synthetic work type (reference skel's per-type size/prio/delay
    tables, ``examples/skel.c:10-40``)."""

    work_type: int
    count: int
    size: int = 64
    prio: int = 0
    delay: float = 0.0


DEFAULT_MIX = tuple(
    TypeSpec(work_type=t, count=12, size=32 * (t + 1), prio=t % 4,
             delay=0.0005 * (t % 3))
    for t in range(1, 9)  # eight types, like the reference skel
)


@dataclasses.dataclass
class SkelResult:
    produced: dict[int, int]
    consumed: dict[int, int]
    ok: bool
    elapsed: float
    tasks_per_sec: float


def run(
    mix: Sequence[TypeSpec] = DEFAULT_MIX,
    num_app_ranks: int = 4,
    nservers: int = 2,
    cfg: Optional[Config] = None,
    timeout: float = 300.0,
) -> SkelResult:
    types = sorted({s.work_type for s in mix})
    delays: dict[int, float] = {}
    produced: dict[int, int] = {}
    for s in mix:  # aggregate: a type may appear in several specs
        delays[s.work_type] = max(delays.get(s.work_type, 0.0), s.delay)
        if s.count > 0:
            produced[s.work_type] = produced.get(s.work_type, 0) + s.count

    def app(ctx):
        counts: dict[int, int] = {}
        if ctx.rank == 0:
            for s in mix:
                body = struct.pack("<i", s.work_type) + b"\0" * max(
                    0, s.size - 4
                )
                for _ in range(s.count):
                    ctx.put(body, s.work_type, work_prio=s.prio)
        t_first = t_last = None
        while True:
            rc, r = ctx.reserve()
            if rc != ADLB_SUCCESS:
                return counts, t_first, t_last
            rc, buf = ctx.get_reserved(r.handle)
            if t_first is None:
                t_first = time.monotonic()
            (t,) = struct.unpack_from("<i", buf)
            assert t == r.work_type, "payload/type mismatch"
            if delays[t]:
                time.sleep(delays[t])
            counts[t] = counts.get(t, 0) + 1
            t_last = time.monotonic()

    res = run_world(
        num_app_ranks,
        nservers,
        types,
        app,
        cfg=cfg or Config(exhaust_check_interval=0.2),
        timeout=timeout,
    )
    consumed: dict[int, int] = {}
    firsts: list[float] = []
    lasts: list[float] = []
    for counts, t_first, t_last in res.app_results.values():
        for t, n in counts.items():
            consumed[t] = consumed.get(t, 0) + n
        if t_first is not None:
            firsts.append(t_first)
            lasts.append(t_last)
    total = sum(consumed.values())
    # makespan over the ranks' own first->last task stamps: excludes world
    # spinup and the exhaustion-termination tail (the hotspot.py convention)
    elapsed = (max(lasts) - min(firsts)) if firsts else 0.0
    return SkelResult(
        produced=produced,
        consumed=consumed,
        ok=consumed == produced,
        elapsed=elapsed,
        tasks_per_sec=total / elapsed if elapsed > 0 else 0.0,
    )
