"""Jacobi grid relaxation — the data-affinity mini-app.

Mirrors the reference pair ``examples/grid_daf.c`` / ``examples/grid_uni.c``:
rank 0 owns the authoritative (nrows+2)×(ncols+2) grid with the boundary set
to ``phi(x, y) = x² − y² + x·y`` (reference ``examples/grid_daf.c:24-28``)
and farms one work unit per row and iteration — payload is the row index,
iteration number, and the row's 3-row neighborhood (reference
``examples/grid_daf.c:107-117``). Any worker (including rank 0) Jacobi-updates
the middle row and sends it back targeted at rank 0 as a type-99 "finished
row" (reference ``examples/grid_daf.c:241-246``). Rank 0 keeps every row in
lock step: only when all rows of an iteration have returned does it re-Put
the next iteration from the updated grid, and after ``niters`` it calls
Set_problem_done (reference ``examples/grid_daf.c:216-240``).

Correctness oracle: :func:`run_sequential` is the uniprocessor reference
(``examples/grid_uni.c``) — the distributed run must reproduce its grid
exactly (same Jacobi averages in a different order).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional

import numpy as np

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

ROW = 0  # reference type 00
DONE_ROW = 99  # reference type 99, routed to rank 0


def make_grid(nrows: int, ncols: int) -> np.ndarray:
    """Boundary = phi, interior = 0 (reference gridinit,
    ``examples/grid_daf.c:152-175``)."""

    def phi(x, y):
        return (x * x) - (y * y) + (x * y)

    g = np.zeros((nrows + 2, ncols + 2), dtype=np.float64)
    for j in range(ncols + 2):
        g[0, j] = phi(1, j + 1)
        g[nrows + 1, j] = phi(nrows + 2, j + 1)
    for i in range(1, nrows + 2):
        g[i, 0] = phi(i + 1, 1)
        g[i, ncols + 1] = phi(i + 1, ncols + 2)
    return g


def jacobi_row(three: np.ndarray) -> np.ndarray:
    """One row's Jacobi update from its 3-row neighborhood (reference
    compute(), ``examples/grid_daf.c:177-193``)."""
    up, mid, down = three
    new = mid.copy()
    new[1:-1] = (up[1:-1] + down[1:-1] + mid[:-2] + mid[2:]) / 4.0
    return new


def run_sequential(nrows: int, ncols: int, niters: int) -> np.ndarray:
    """The uniprocessor oracle (reference ``examples/grid_uni.c``)."""
    g = make_grid(nrows, ncols)
    for _ in range(niters):
        new = g.copy()
        for i in range(1, nrows + 1):
            new[i] = jacobi_row(g[i - 1 : i + 2])
        g = new
    return g


@dataclasses.dataclass
class GridResult:
    grid: np.ndarray
    average: float
    rows_computed: dict[int, int]  # rank -> row updates performed


def _pack(row_idx: int, it: int, rows: np.ndarray) -> bytes:
    """ROW units carry the 3-row neighborhood; DONE_ROW units carry only the
    updated middle row (rank 0 reads nothing else)."""
    return struct.pack("<ii", row_idx, it) + rows.tobytes()


def _unpack(buf: bytes, ncols: int) -> tuple[int, int, np.ndarray]:
    row_idx, it = struct.unpack_from("<ii", buf)
    arr = np.frombuffer(buf, dtype=np.float64, offset=8).reshape(-1, ncols + 2)
    return row_idx, it, arr


def run(
    nrows: int = 8,
    ncols: int = 8,
    niters: int = 4,
    num_app_ranks: int = 3,
    nservers: int = 1,
    cfg: Optional[Config] = None,
    timeout: float = 120.0,
) -> GridResult:
    out: dict = {}

    def app(ctx):
        computed = 0
        if ctx.rank == 0:
            grid = make_grid(nrows, ncols)
            it = 1
            rows_back = 0
            if niters < 1:  # match the oracle: zero iterations = untouched grid
                ctx.set_problem_done()
                out["grid"] = grid
                return computed
            ctx.begin_batch_put(b"")
            for i in range(1, nrows + 1):
                ctx.put(_pack(i, it, grid[i - 1 : i + 2]), ROW)
            ctx.end_batch_put()
            while True:
                rc, r = ctx.reserve()
                if rc != ADLB_SUCCESS:
                    break
                rc, buf = ctx.get_reserved(r.handle)
                if r.work_type == DONE_ROW:
                    row_idx, row_it, rows = _unpack(buf, ncols)
                    grid[row_idx] = rows[0]
                    rows_back += 1
                    if rows_back == nrows:
                        rows_back = 0
                        it += 1
                        if it > niters:
                            ctx.set_problem_done()
                        else:
                            for i in range(1, nrows + 1):
                                ctx.put(_pack(i, it, grid[i - 1 : i + 2]), ROW)
                else:  # rank 0 is also a worker (reference work() on rank 0)
                    computed += _work_one(ctx, buf)
            out["grid"] = grid
            return computed
        while True:
            rc, r = ctx.reserve([ROW])
            if rc != ADLB_SUCCESS:
                return computed
            rc, buf = ctx.get_reserved(r.handle)
            computed += _work_one(ctx, buf)

    def _work_one(ctx, buf: bytes) -> int:
        row_idx, it, three = _unpack(buf, ncols)
        new_mid = jacobi_row(three)
        ctx.put(_pack(row_idx, it, new_mid), DONE_ROW, work_prio=99,
                target_rank=0)
        return 1

    res = run_world(
        num_app_ranks,
        nservers,
        [ROW, DONE_ROW],
        app,
        cfg=cfg or Config(exhaust_check_interval=0.25),
        timeout=timeout,
    )
    grid = out["grid"]
    return GridResult(
        grid=grid,
        average=float(grid[1:-1, 1:-1].mean()),
        rows_computed=dict(res.app_results),
    )
