"""Sudoku on the all-native plane: C clients (``examples/sudoku_c.c``)
against the C++ server daemons — multi-type reserve with a collector
rank at OS-process scale (reference ``examples/sudoku.c``).  The
harness supplies digit-relabeled isomorphs of the puzzle (one source of
truth with the in-proc port) and re-validates every echoed solution."""

from __future__ import annotations

import dataclasses
from typing import Optional

from adlb_tpu.runtime.world import Config
from adlb_tpu.workloads.sudoku import (
    DEFAULT_PUZZLE,
    _relabel,
    check_solution,
)


@dataclasses.dataclass
class SudokuNativeResult:
    valid: bool  # every puzzle solved and every solution validated twice
    solved: int
    tasks: int  # boards expanded across worker ranks
    elapsed: float
    tasks_per_sec: float
    wait_pct: float


def run(
    puzzle: str = DEFAULT_PUZZLE,
    n_puzzles: int = 1,
    num_app_ranks: int = 4,
    nservers: int = 2,
    cfg: Optional[Config] = None,
    timeout: float = 300.0,
) -> SudokuNativeResult:
    from adlb_tpu.native.capi import (
        parse_probe_lines,
        probe_aggregate,
        run_native_probe,
    )

    if num_app_ranks < 2:
        # rank 0 is a dedicated collector (reserves only SOLUTION); with
        # no worker ranks the WORK pool can never drain and the world
        # hangs until the timeout — fail fast instead
        raise ValueError("sudoku_native needs num_app_ranks >= 2")
    if n_puzzles > 64:
        raise ValueError("sudoku_c.c caps puzzles per run at 64 (MAXP)")
    puzzles = [puzzle] + [
        _relabel(puzzle, seed) for seed in range(1, n_puzzles)
    ]
    results = run_native_probe(
        "sudoku_c.c",
        types=[1, 2],
        env_extra={"ADLB_SUDOKU_PUZZLES": ",".join(puzzles)},
        num_app_ranks=num_app_ranks,
        nservers=nservers,
        cfg=cfg,
        timeout=timeout,
    )
    # rank 0's exit code already enforced its in-C validation
    # (run_native_probe raises on nonzero); re-check the echoed boards
    # here so harness and client validations are independent
    sols = {}
    for ln in results[0][1].splitlines():
        if ln.startswith("SUDSOL "):
            kv = dict(f.split("=") for f in ln.split()[1:])
            sols[int(kv["pid"])] = bytes(int(ch) for ch in kv["board"])
    valid = len(sols) == len(puzzles) and all(
        check_solution(sols[pid], puzzles[pid]) for pid in sols
    )
    rows = parse_probe_lines(results, "SUD")
    # rank 0 is a dedicated SOLUTION collector (done=0, blocked most of
    # the makespan by design): keep it in the makespan, exclude it from
    # the wait average — same treatment as hotspot_native's producer
    tasks, elapsed, rate, wait_pct = probe_aggregate(
        rows, wait_rows=rows[1:]
    )
    return SudokuNativeResult(
        valid=valid,
        solved=rows[0]["solved"],
        tasks=tasks,
        elapsed=elapsed,
        tasks_per_sec=rate,
        wait_pct=wait_pct,
    )
