"""Hotspot: producer-concentrated load that must be rebalanced to be fast.

The scenario the global balancer exists for (BASELINE.json north star): all
work enters at one server (data-locality routing, ``put_routing="home"``)
while consumers are spread across every server. Throughput is then limited
by how quickly cross-server balancing moves work to parked workers — the
reference's answer is qmstat-guided RFR stealing (reference
``src/adlb.c:1802-2070``); this framework's answer is the batched global
solve. Work is a GIL-free sleep so the in-process harness measures balancing,
not Python compute.

Reports tasks/sec and mean worker busy-fraction (1 - idle%), the BASELINE.md
metrics.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

TOKEN = 1


@dataclasses.dataclass
class HotspotResult:
    tasks: int
    elapsed: float
    tasks_per_sec: float
    busy_fraction: float  # mean over workers (NOMINAL compute / elapsed)
    idle_pct: float
    # mean fraction of the makespan workers spent blocked acquiring work
    # (Reserve+Get) — the steal-to-exec quantity, measured directly;
    # 0.0 where the workload does not report it
    wait_pct: float = 0.0


def run(
    n_tasks: int = 300,
    work_time: float = 0.004,
    num_app_ranks: int = 8,
    nservers: int = 4,
    cfg: Optional[Config] = None,
    timeout: float = 300.0,
    fused: bool = True,
    batch: int = 4,
) -> HotspotResult:
    """``fused=True`` (default) consumes via the fused ``get_work_batch``
    call (up to ``batch`` units per round trip, inlined only when the
    units are LOCAL to the responding server) — both modes issue the
    identical call, so the mode that pre-positions work locally is paid
    for that locality, which is the quantity this scenario measures.
    ``fused=False`` keeps the two-call Reserve + Get_reserved loop (the
    reference's only consumer shape, ``src/adlb.c:2868-3025``) for
    comparability with earlier rounds."""
    base = cfg or Config()
    cfg = dataclasses.replace(
        base,
        put_routing="home",
        exhaust_check_interval=min(base.exhaust_check_interval, 0.2),
    )

    def app(ctx):
        if ctx.rank == 0:
            # all tokens land on rank 0's home server
            t_first = time.monotonic()
            for i in range(n_tasks):
                ctx.put(b"w", TOKEN, work_prio=0)
            return t_first, t_first, 0, 0.0
        done = 0
        busy = 0.0
        t_start = time.monotonic()
        t_last = t_start
        while True:
            if fused:
                rc, got = ctx.get_work_batch([TOKEN], max_units=batch)
            else:
                rc, r = ctx.reserve([TOKEN])
            if rc != ADLB_SUCCESS:
                # makespan measured to the last completed task; the
                # exhaustion-termination tail is excluded (it is a constant,
                # not a balancing cost)
                return t_start, t_last, done, busy
            n_units = len(got) if fused else 1
            if not fused:
                rc, buf = ctx.get_reserved(r.handle)
            for _ in range(n_units):
                time.sleep(work_time)  # GIL-free "compute"
                # NOMINAL busy (see hotspot_native: wall-clock busy counts
                # scheduler/GIL delay inside the sleep as utilization,
                # which inverts idle% against throughput under contention)
                busy += work_time
                done += 1
                t_last = time.monotonic()

    res = run_world(num_app_ranks, nservers, [TOKEN], app, cfg=cfg,
                    timeout=timeout)
    workers = [v for k, v in res.app_results.items() if k != 0 and v]
    tasks = sum(w[2] for w in workers)
    t_begin = min(v[0] for v in res.app_results.values())
    t_end = max(w[1] for w in workers)
    elapsed = max(t_end - t_begin, 1e-9)
    busy = sum(w[3] / elapsed for w in workers) / len(workers) if workers else 0.0
    return HotspotResult(
        tasks=tasks,
        elapsed=elapsed,
        tasks_per_sec=tasks / elapsed,
        busy_fraction=busy,
        idle_pct=100.0 * (1.0 - busy),
    )
