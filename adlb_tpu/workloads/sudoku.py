"""Sudoku DFS: known-answer board solve through the pool.

Mirrors the reference's approach (reference ``examples/sudoku.c``): a work
unit is a whole board; a worker picks the most-constrained empty cell,
Puts one child board per legal digit (priority = number of filled cells, so
nearly-complete boards are preferred), and a completed board is sent to rank
0 as a max-priority targeted SOLUTION unit. Rank 0 validates the solution and
declares the problem done (reference prints the solved board,
``examples/sudoku.c:283-287``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

WORK = 1
SOLUTION = 2

# A standard 9x9 puzzle (0 = empty) with a unique solution.
DEFAULT_PUZZLE = (
    "530070000"
    "600195000"
    "098000060"
    "800060003"
    "400803001"
    "700020006"
    "060000280"
    "000419005"
    "000080079"
)


def _candidates(board: bytes, idx: int) -> list[int]:
    r, c = divmod(idx, 9)
    used = set()
    for i in range(9):
        used.add(board[r * 9 + i])
        used.add(board[i * 9 + c])
    br, bc = 3 * (r // 3), 3 * (c // 3)
    for i in range(3):
        for j in range(3):
            used.add(board[(br + i) * 9 + (bc + j)])
    return [d for d in range(1, 10) if d not in used]


def _most_constrained(board: bytes) -> tuple[int, list[int]]:
    best_idx, best_cands = -1, None
    for i in range(81):
        if board[i] == 0:
            cands = _candidates(board, i)
            if best_cands is None or len(cands) < len(best_cands):
                best_idx, best_cands = i, cands
                if len(cands) <= 1:
                    break
    return best_idx, best_cands if best_cands is not None else []


def check_solution(board: bytes, puzzle: str) -> bool:
    for i in range(81):
        given = int(puzzle[i])
        if given and board[i] != given:
            return False
    want = set(range(1, 10))
    for r in range(9):
        if {board[r * 9 + c] for c in range(9)} != want:
            return False
    for c in range(9):
        if {board[r * 9 + c] for r in range(9)} != want:
            return False
    for br in range(3):
        for bc in range(3):
            cells = {
                board[(3 * br + i) * 9 + (3 * bc + j)]
                for i in range(3)
                for j in range(3)
            }
            if cells != want:
                return False
    return True


@dataclasses.dataclass
class SudokuResult:
    solution: bytes
    valid: bool
    tasks_processed: int
    elapsed: float


def _relabel(puzzle: str, seed: int) -> str:
    """Digit-relabeled isomorph: permuting the digit alphabet preserves
    sudoku validity but reorders every candidate list, giving a distinct
    search tree — a cheap way to batch independent instances."""
    import random

    perm = list(range(1, 10))
    random.Random(seed).shuffle(perm)
    table = {"0": "0"}
    for i, p in enumerate(perm):
        table[str(i + 1)] = str(p)
    return "".join(table[ch] for ch in puzzle)


def run(
    puzzle: str = DEFAULT_PUZZLE,
    num_app_ranks: int = 4,
    nservers: int = 2,
    cfg: Optional[Config] = None,
    timeout: float = 120.0,
    n_puzzles: int = 1,
) -> SudokuResult:
    """Solve ``n_puzzles`` digit-relabeled isomorphs of ``puzzle`` in one
    world (board payloads carry a puzzle-id byte). Batching keeps the pool
    busy long enough that first-solution search luck and the serial warmup
    average out — single-instance runs are rate-noise at benchmark scale."""
    puzzles = [puzzle] + [
        _relabel(puzzle, seed) for seed in range(1, n_puzzles)
    ]
    starts = [
        bytes(int(ch) for ch in p) + bytes([pid])
        for pid, p in enumerate(puzzles)
    ]

    def app(ctx):
        processed = 0
        if ctx.rank == 0:
            for pid, s in enumerate(starts):
                ctx.put(s, WORK, work_prio=sum(1 for b in s[:81] if b))
            # rank 0 collects one solution per puzzle (reference nq/sudoku
            # pattern: collector rank + workers)
            sols: dict[int, bytes] = {}
            while len(sols) < len(starts):
                rc, r = ctx.reserve([SOLUTION])
                if rc != ADLB_SUCCESS:
                    break
                rc, buf = ctx.get_reserved(r.handle)
                sols.setdefault(buf[81], bytes(buf[:81]))
            ctx.set_problem_done()
            return sols, processed
        while True:
            rc, r = ctx.reserve([WORK])
            if rc != ADLB_SUCCESS:
                return None, processed
            rc, buf = ctx.get_reserved(r.handle)
            processed += 1
            board, pid = bytes(buf[:81]), buf[81]
            idx, cands = _most_constrained(board)
            if idx < 0:  # solved
                ctx.put(buf, SOLUTION, 999999999, target_rank=0)
                continue
            filled = sum(1 for b in board if b)
            for d in cands:
                child = bytearray(buf)
                child[idx] = d
                ctx.put(bytes(child), WORK, work_prio=filled + 1)

    t0 = time.monotonic()
    res = run_world(
        num_app_ranks,
        nservers,
        [WORK, SOLUTION],
        app,
        cfg=cfg or Config(exhaust_check_interval=0.2),
        timeout=timeout,
    )
    elapsed = time.monotonic() - t0
    sols = res.app_results[0][0] or {}
    tasks = sum(v[1] for v in res.app_results.values())
    valid = len(sols) == len(puzzles) and all(
        check_solution(sols[pid], puzzles[pid]) for pid in sols
    )
    return SudokuResult(
        solution=sols.get(0),
        valid=valid,
        tasks_processed=tasks,
        elapsed=elapsed,
    )
