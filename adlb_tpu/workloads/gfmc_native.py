"""GFMC A/B/C/D economy on the all-native plane: C clients
(``examples/gfmc_c.c``) against the C++ server daemons — the reference
c4 mini-app's answer economy (reference ``examples/c4.c:31-37``) at
OS-process scale.  The C master self-checks the checksum (nonzero exit
on mismatch); the harness independently checks the package counts."""

from __future__ import annotations

import dataclasses
from typing import Optional

from adlb_tpu.runtime.world import Config


@dataclasses.dataclass
class GfmcNativeResult:
    ok: bool
    counts: dict
    expected: dict
    tasks: int
    elapsed: float
    tasks_per_sec: float
    wait_pct: float


def run(
    num_a: int = 6,
    bs_per_a: int = 4,
    cs_per_b: int = 3,
    num_app_ranks: int = 4,
    nservers: int = 2,
    cfg: Optional[Config] = None,
    timeout: float = 300.0,
) -> GfmcNativeResult:
    from adlb_tpu.native.capi import (
        parse_probe_lines,
        probe_aggregate,
        run_native_probe,
    )

    if num_app_ranks < 2:
        # the master is a dedicated collector (reserves only TYPE_D);
        # with no worker ranks the economy can never run — fail fast
        raise ValueError("gfmc_native needs num_app_ranks >= 2")
    expected = {
        "a": num_a,
        "b": num_a * bs_per_a,
        "c": num_a * bs_per_a * cs_per_b,
        "d": num_a * bs_per_a,
    }
    results = run_native_probe(
        "gfmc_c.c",
        types=[1, 2, 3, 4, 5],
        env_extra={
            "ADLB_GFMC_NA": str(num_a),
            "ADLB_GFMC_BPA": str(bs_per_a),
            "ADLB_GFMC_CPB": str(cs_per_b),
        },
        num_app_ranks=num_app_ranks,
        nservers=nservers,
        cfg=cfg,
        timeout=timeout,
    )
    rows = parse_probe_lines(results, "GFMC")
    counts = {
        k: sum(r[k] for r in rows) for k in ("a", "b", "c", "d")
    }
    # throughput counts every unit a worker consumed, including C-answer
    # receptions (outside the package-count check but real queue traffic).
    # The master (rank 0) is a dedicated collector blocked in Reserve for
    # nearly the whole makespan by design — its row stays in the makespan
    # but is excluded from the wait average (as hotspot_native excludes
    # its producer), else wait_pct carries a ~1/num_app_ranks floor that
    # says nothing about balancing.
    tasks = sum(counts.values()) + sum(r["ans"] for r in rows)
    tasks, elapsed, rate, wait_pct = probe_aggregate(
        rows, tasks=tasks, wait_rows=rows[1:]
    )
    return GfmcNativeResult(
        ok=all(counts[k] == expected[k] for k in expected),
        counts=counts,
        expected=expected,
        tasks=tasks,
        elapsed=elapsed,
        tasks_per_sec=rate,
        wait_pct=wait_pct,
    )
