"""Trickle on the all-native plane: C clients + C++ server daemons (+ JAX
balancer sidecar in tpu mode), every rank its own OS process.

The in-process trickle probe measures cross-server dispatch latency with
all ranks GIL-coupled in one interpreter; this twin removes that coupling
— the data path is entirely C/C++, and the only Python in the world is
the balancer brain. Scenario shape and metrics match
:mod:`adlb_tpu.workloads.trickle` (steady arrival at one server via home
routing, consumers parked elsewhere; reference analogue: the steady-state
skel shape, reference ``examples/skel.c:10-40``).
"""

from __future__ import annotations

from typing import Optional

from adlb_tpu.runtime.world import Config
from adlb_tpu.workloads.trickle import TrickleResult


def run(
    n_tasks: int = 240,
    interval_us: int = 10000,
    group: int = 2,
    work_us: int = 2000,
    num_app_ranks: int = 8,
    nservers: int = 4,
    cfg: Optional[Config] = None,
    timeout: float = 300.0,
) -> TrickleResult:
    from adlb_tpu.native.capi import run_native_probe

    results = run_native_probe(
        "trickle_c.c",
        types=[1, 2],  # TOKEN + the co-homed ranks' NEVER parking type
        env_extra={
            # home routing concentrates the producer's puts on one server,
            # so every delivery to the (remote) consumers is a cross-server
            # dispatch — the latency under test
            "ADLB_PUT_ROUTING": "home",
            "ADLB_TRICK_NTASKS": str(n_tasks),
            "ADLB_TRICK_INTERVAL_US": str(interval_us),
            "ADLB_TRICK_GROUP": str(group),
            "ADLB_TRICK_WORK_US": str(work_us),
        },
        num_app_ranks=num_app_ranks,
        nservers=nservers,
        cfg=cfg,
        timeout=timeout,
    )
    lats: list = []
    tasks = 0
    for _rc, out, _err in results:
        line = next(ln for ln in out.splitlines() if ln.startswith("TRICK "))
        n = int(line.split("n=")[1].split()[0])
        tasks += n
        vals = line.split("lat_ms=")[1].split()
        lats.extend(float(v) for v in vals)
    if tasks != n_tasks:
        raise RuntimeError(f"trickle_native: lost work ({tasks}/{n_tasks})")
    lats.sort()

    def p(q: float) -> float:
        return lats[min(int(q * len(lats)), len(lats) - 1)] if lats else 0.0

    # elapsed is arrival-paced, not a throughput measure here
    elapsed = n_tasks / max(group, 1) * (interval_us * 1e-6)
    return TrickleResult(
        tasks=tasks,
        elapsed=elapsed,
        tasks_per_sec=tasks / max(elapsed, 1e-9),
        dispatch_p50_ms=p(0.50),
        dispatch_p90_ms=p(0.90),
    )
