"""partest — synthetic-work calibration utility.

Mirrors the reference ``examples/partest.c``: ``define_work(secs)`` runs a
triply-nested loop over an indivisible ``nugget()`` (a short fixed burst of
floating-point work, reference ``examples/partest.c:115-123``) under a clock
until ``secs`` have elapsed, returning the loop indices ``(i, j, k)`` reached;
``do_work(i, j, k)`` replays those indices without consulting the clock, so
the replay takes (approximately) the calibrated wall time on a same-speed
machine. The reference uses this to parameterize synthetic workloads (skel /
c2 style "work that takes N seconds") portably across machines; its main()
then replays the unit on every rank and reports the parallel speedup.

The pure-Python nugget here is far slower per call than the C one, so the
loop limit is kept but the indices come out smaller; the contract — replay
time tracks calibration time — is what the tests check.
"""

from __future__ import annotations

import dataclasses
import math
import time

LOOPLIMIT = 100_000  # reference examples/partest.c:12


def nugget(_reps: int = 1000) -> float:
    """The indivisible unit of work (reference examples/partest.c:115-123)."""
    x = 0.0
    for i in range(_reps):
        x = math.sqrt(math.sqrt(math.sqrt(float(i)) + math.sqrt(float(i + 1))))
        x = math.sqrt(math.sqrt(math.sqrt(float(i + 2)) + math.sqrt(float(i + 3))))
    return x


@dataclasses.dataclass
class WorkUnit:
    """A calibrated synthetic work unit: replaying (i, j, k) nuggets takes
    roughly the wall time passed to define_work."""

    i: int
    j: int
    k: int
    calibrated_secs: float


def define_work(secs: float, nugget_reps: int = 1000) -> WorkUnit:
    """Run nuggets under the clock until `secs` elapse; record the indices
    (reference examples/partest.c:69-90)."""
    start = time.perf_counter()
    i = j = k = 0
    done = False
    for i in range(LOOPLIMIT):
        for j in range(LOOPLIMIT):
            for k in range(LOOPLIMIT):
                nugget(nugget_reps)
                if time.perf_counter() - start >= secs:
                    done = True
                    break
            if done:
                break
        if done:
            break
    return WorkUnit(i=i, j=j, k=k, calibrated_secs=secs)


def do_work(unit: WorkUnit, nugget_reps: int = 1000) -> float:
    """Replay a calibrated unit without the clock; returns elapsed seconds
    (reference examples/partest.c:92-112)."""
    start = time.perf_counter()
    for _ in range(unit.i + 1):
        for _ in range(unit.j + 1):
            for _ in range(unit.k + 1):
                nugget(nugget_reps)
    return time.perf_counter() - start
