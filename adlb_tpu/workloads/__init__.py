"""Workload programs: the framework's "model zoo".

The reference ships its test/benchmark story as example mini-apps
(reference ``examples/``, SURVEY §4): self-checking known-answer programs
that exercise the full Put/Reserve/answer economy. These are their
re-designed equivalents, each a parameterizable function over
:func:`adlb_tpu.api.run_world`, used both as integration tests and as
benchmark drivers:

* :mod:`~adlb_tpu.workloads.nq` — n-queens DFS (reference ``examples/nq.c``)
* :mod:`~adlb_tpu.workloads.tsp` — branch-and-bound TSP with tree-broadcast
  bound updates (reference ``examples/tsp.c``)
* :mod:`~adlb_tpu.workloads.sudoku` — multi-type DFS (reference
  ``examples/sudoku.c``)
* :mod:`~adlb_tpu.workloads.batcher` — heterogeneous job bag (reference
  ``examples/batcher.c``)
* :mod:`~adlb_tpu.workloads.gfmc` — A/B/C/D work-package economy with
  self-validating counts (reference ``examples/c4.c``)
* :mod:`~adlb_tpu.workloads.coinop` — pop-latency probe (reference
  ``examples/coinop.cpp``)
* :mod:`~adlb_tpu.workloads.grid` — data-affinity Jacobi relaxation with a
  sequential oracle (reference ``examples/grid_daf.c`` / ``grid_uni.c``)
* :mod:`~adlb_tpu.workloads.add2` — answer-economy smoke test (reference
  ``examples/add2.c``)
* :mod:`~adlb_tpu.workloads.skel` — 8-type synthetic stress probe
  (reference ``examples/skel.c`` / ``c2.c``)
* :mod:`~adlb_tpu.workloads.hotspot` — producer-concentrated balancing
  scenario (no reference analogue; the BASELINE.json north-star probe)
* :mod:`~adlb_tpu.workloads.pmcmc` — embarrassingly-parallel MCMC hard-disk
  demo with targeted solution returns (reference ``examples/pmcmc.c``)

The reference's ``c1.c``/``c2.c``/``c3.c`` are evolutionary precursors of
``c4.c`` (the same GFMC A/B/C economy with fewer stages / app_comm answer
plumbing); their behavior is covered by :mod:`~adlb_tpu.workloads.gfmc` and
:mod:`~adlb_tpu.workloads.skel`. ``model.c`` (master puts N dummy problems,
everyone reserves any-type and sleeps, exhaustion terminates) is the same
shape as :mod:`~adlb_tpu.workloads.hotspot`. ``partest.c`` is an unfinished
scratch program in the reference (``examples/partest.c:1-3`` says so
itself); ``stats.c`` is a standalone statistics library, ported as
:mod:`adlb_tpu.utils.stats`.
"""
