"""Workload programs: the framework's "model zoo".

The reference ships its test/benchmark story as example mini-apps
(reference ``examples/``, SURVEY §4): self-checking known-answer programs
that exercise the full Put/Reserve/answer economy. These are their
re-designed equivalents, each a parameterizable function over
:func:`adlb_tpu.api.run_world`, used both as integration tests and as
benchmark drivers:

* :mod:`~adlb_tpu.workloads.nq` — n-queens DFS (reference ``examples/nq.c``)
* :mod:`~adlb_tpu.workloads.tsp` — branch-and-bound TSP with tree-broadcast
  bound updates (reference ``examples/tsp.c``)
* :mod:`~adlb_tpu.workloads.sudoku` — multi-type DFS (reference
  ``examples/sudoku.c``)
* :mod:`~adlb_tpu.workloads.batcher` — heterogeneous job bag (reference
  ``examples/batcher.c``)
* :mod:`~adlb_tpu.workloads.gfmc` — A/B/C/D work-package economy with
  self-validating counts (reference ``examples/c4.c``)
* :mod:`~adlb_tpu.workloads.coinop` — pop-latency probe (reference
  ``examples/coinop.cpp``)
* :mod:`~adlb_tpu.workloads.grid` — data-affinity Jacobi relaxation with a
  sequential oracle (reference ``examples/grid_daf.c`` / ``grid_uni.c``)
* :mod:`~adlb_tpu.workloads.add2` — answer-economy smoke test (reference
  ``examples/add2.c``)
* :mod:`~adlb_tpu.workloads.skel` — 8-type synthetic stress probe
  (reference ``examples/skel.c`` / ``c2.c``)
* :mod:`~adlb_tpu.workloads.hotspot` — producer-concentrated balancing
  scenario (no reference analogue; the BASELINE.json north-star probe)
* :mod:`~adlb_tpu.workloads.trickle` — steady single-server work arrival
  with remote-only consumers, isolating dispatch/discovery latency (no
  reference analogue; the steal-to-exec-latency probe of BASELINE.md)
* :mod:`~adlb_tpu.workloads.hotspot_native` /
  :mod:`~adlb_tpu.workloads.trickle_native` — the two probes above on the
  all-native plane (C clients ``examples/hotspot_c.c`` /
  ``examples/trickle_c.c``, C++ daemons, JAX sidecar), for scale and
  latency numbers free of interpreter coupling
* :mod:`~adlb_tpu.workloads.pmcmc` — embarrassingly-parallel MCMC hard-disk
  demo with targeted solution returns (reference ``examples/pmcmc.c``)

* :mod:`~adlb_tpu.workloads.model` — minimal master/worker dummy-work model
  terminating by exhaustion (reference ``examples/model.c``)
* :mod:`~adlb_tpu.workloads.c1` — GFMC-precursor epoch workload whose B/C
  answers travel as app-to-app point-to-point messages, exercising the
  app_comm-equivalent messaging layer (reference ``examples/c1.c``)
* :mod:`~adlb_tpu.workloads.c3` — batch-generation GFMC variant with a
  park-until-exhaustion master (reference ``examples/c3.c``)
* :mod:`~adlb_tpu.workloads.partest` — synthetic-work calibration utility
  (define_work/do_work nugget loops, reference ``examples/partest.c``)

``c2.c`` is the skeleton behind :mod:`~adlb_tpu.workloads.skel` and is
covered there; ``stats.c`` is a standalone statistics library, ported as
:mod:`adlb_tpu.utils.stats`; ``grid_old_daf.c`` is a superseded draft
whose own header says it "does not agree with grid_uni in terms of
computed result" (reference ``examples/grid_old_daf.c:1-8``) — the
corrected algorithm is :mod:`~adlb_tpu.workloads.grid`; ``f1.f`` /
``fbatcher.f`` are Fortran twins of c1/batcher exercising the Fortran
binding, which this framework validates through the C shim tests instead
(``tests/test_fshim.py``).
"""
