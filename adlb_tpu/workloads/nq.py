"""n-queens: recursive DFS over the shared pool.

Mirrors the reference's decomposition (reference ``examples/nq.c:74-140``):
a work unit is a partial board (one queen row per filled column); a worker
expands the first open column, re-Putting each safe child with priority equal
to the column index — deeper subproblems get higher priority, giving the pool
depth-first flavor — until the cutoff depth ``max_depth_for_puts``, below
which it solves the subtree locally. Workers keep local solution counts and
the world terminates by exhaustion (reference nq's quiet mode); the driver
sums and validates against the known answer.
"""

from __future__ import annotations

import dataclasses
import struct
import time
from typing import Optional

from adlb_tpu.api import run_world
from adlb_tpu.runtime.world import Config
from adlb_tpu.types import ADLB_SUCCESS

WORK = 1

KNOWN_SOLUTIONS = {
    4: 2, 5: 10, 6: 4, 7: 40, 8: 92, 9: 352, 10: 724,
    # scale rows for the native harness (OEIS A000170)
    11: 2680, 12: 14200, 13: 73712, 14: 365596,
}


def _safe(col: int, row: int, rows: list[int]) -> bool:
    for c in range(col):
        r = rows[c]
        if r == row or r + c == col + row or c - r == col - row:
            return False
    return True


def _count_subtree(n: int, rows: list[int], col: int) -> int:
    if col == n:
        return 1
    total = 0
    for row in range(n):
        if _safe(col, row, rows):
            rows[col] = row
            total += _count_subtree(n, rows, col + 1)
            rows[col] = -1
    return total


@dataclasses.dataclass
class NqResult:
    solutions: int
    tasks_processed: int
    puts: int
    elapsed: float
    tasks_per_sec: float


def app_main(ctx, n: int, max_depth_for_puts: int):
    """Per-rank worker body: returns (solutions, tasks_processed, puts)."""
    fmt = f"<{n}i"
    processed = 0
    puts = 0
    solutions = 0
    if ctx.rank == 0:
        ctx.put(struct.pack(fmt, *([-1] * n)), WORK, work_prio=0)
        puts += 1
    while True:
        rc, r = ctx.reserve([WORK])
        if rc != ADLB_SUCCESS:
            return solutions, processed, puts
        rc, buf = ctx.get_reserved(r.handle)
        rows = list(struct.unpack(fmt, buf))
        processed += 1
        col = n
        for i in range(n):
            if rows[i] < 0:
                col = i
                break
        if col <= max_depth_for_puts and col < n:
            for row in range(n):
                if _safe(col, row, rows):
                    rows[col] = row
                    ctx.put(struct.pack(fmt, *rows), WORK, work_prio=col)
                    puts += 1
                    rows[col] = -1
        else:
            solutions += _count_subtree(n, rows, col)


def run(
    n: int = 8,
    num_app_ranks: int = 4,
    nservers: int = 2,
    max_depth_for_puts: int = 2,
    cfg: Optional[Config] = None,
    timeout: float = 120.0,
) -> NqResult:
    def app(ctx):
        return app_main(ctx, n, max_depth_for_puts)

    t0 = time.monotonic()
    res = run_world(
        num_app_ranks,
        nservers,
        [WORK],
        app,
        cfg=cfg or Config(exhaust_check_interval=0.15),
        timeout=timeout,
    )
    elapsed = time.monotonic() - t0
    solutions = sum(v[0] for v in res.app_results.values())
    tasks = sum(v[1] for v in res.app_results.values())
    puts = sum(v[2] for v in res.app_results.values())
    return NqResult(
        solutions=solutions,
        tasks_processed=tasks,
        puts=puts,
        elapsed=elapsed,
        tasks_per_sec=tasks / elapsed if elapsed > 0 else 0.0,
    )
