"""coinop on the all-native plane: the pop-latency microbenchmark as C
client processes (``examples/coinop_c.c``) against the C++ server
daemons — the fork's own steal-to-exec latency probe (reference
``examples/coinop.cpp:79-126,190-213``) with the GIL coupling of the
in-process twin (:mod:`adlb_tpu.workloads.coinop`) removed.

Each C worker prints its Welford mean/stddev (the moments the reference
gathers to its producer via MPI_Gather) plus the raw per-pop latencies;
the harness gathers both, validates that no token was lost, and returns
the same :class:`~adlb_tpu.workloads.coinop.CoinopResult` shape so the
two planes' numbers are directly comparable.
"""

from __future__ import annotations

from typing import Optional

from adlb_tpu.runtime.world import Config
from adlb_tpu.workloads.coinop import CoinopResult


def run(
    n_tokens: int = 400,
    num_app_ranks: int = 4,
    nservers: int = 2,
    token_bytes: int = 64,
    work_us: int = 0,
    cfg: Optional[Config] = None,
    timeout: float = 300.0,
) -> CoinopResult:
    from adlb_tpu.native.capi import (
        parse_probe_lines,
        probe_makespan,
        run_native_probe,
    )

    results = run_native_probe(
        "coinop_c.c",
        types=[1],
        env_extra={
            "ADLB_COIN_NTOKENS": str(n_tokens),
            "ADLB_COIN_BYTES": str(token_bytes),
            "ADLB_COIN_WORK_US": str(work_us),
        },
        num_app_ranks=num_app_ranks,
        nservers=nservers,
        cfg=cfg,
        timeout=timeout,
    )
    rows = parse_probe_lines(results, "COIN")
    all_lats: list[float] = []
    for _rc, out, _err in results:
        line = next(
            ln for ln in out.splitlines() if ln.startswith("COINLAT")
        )
        all_lats.extend(float(v) for v in line.split()[1:])
    pops = sum(r["pops"] for r in rows)
    if pops != n_tokens or len(all_lats) != n_tokens:
        raise RuntimeError(
            f"coinop_native: lost work (pops={pops}, "
            f"lats={len(all_lats)}, want {n_tokens})"
        )
    all_lats.sort()
    per_worker = {
        r["rank"]: (float(r["mean_ms"]), float(r["stddev_ms"]))
        for r in rows
        if r["rank"] != 0 and r["pops"]
    }
    _t0, _t1, elapsed = probe_makespan(rows)
    n = len(all_lats)
    return CoinopResult(
        pops=n,
        latency_mean_ms=sum(all_lats) / n,
        latency_p50_ms=all_lats[n // 2],
        latency_p95_ms=all_lats[min(int(n * 0.95), n - 1)],
        per_worker=per_worker,
        elapsed=elapsed,
        pops_per_sec=n / elapsed,
    )
