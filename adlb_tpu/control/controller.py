"""Closed-loop fleet controller: the "act" layer of sense → decide → act.

PRs 12/13 gave the master a merged fleet registry and tail-promoted
journeys (sense); PR 16's SLO engine turned them into a durable alert
lifecycle (decide); PR 15 left the actuators — server scale-out/in
through the membership plane, per-tenant quota throttling through the
job machinery — waiting for a brain. This module closes the loop: a
master-side :class:`Controller` rides the obs tick exactly like the SLO
engine, watches the fleet signals (memory pressure per rank, put-backoff
counts, lease ages, per-job queue depth/age, FIRING alerts), and drives
the existing actuators under **explicit hysteresis**:

* **per-action cooldowns** — after an action (or a dry-run would-act),
  its cooldown key is stamped for ``control_cooldown_s``; a flapping
  metric produces at most one action per cooldown window. ``scale_out``
  and ``scale_in`` SHARE one key, so the controller can never bounce a
  shard out and back in inside a window; throttles key per tenant.
* **fleet-size bounds** — ``control_min_servers`` /
  ``control_max_servers`` (0 = unbounded) are hard rails: a rule that
  would cross them records outcome ``bounded`` and does nothing.
* **epoch-churn hold** — membership epoch bumps freeze actions for the
  same grace window the SLO engine freezes alert state (an enacted
  scale-out's own join churn thus self-holds the controller while the
  new shard warms).
* **dry-run** (``control_dry_run=True``) — every decision is computed,
  recorded, and cooldown-paced exactly as live, but outcome is
  ``dry_run`` and no actuator is touched.

**Every decision is a record** — inputs → rule → action → outcome —
appended to a bounded history the ops endpoint serves at
``GET /control`` (and the reactor mirrors into the flight recorder).
``POST /control`` tweaks the live policy (thresholds, bounds, cooldown,
dry_run) without a restart.

Decision rules (deliberately few, explicit, and unit-testable —
:func:`Controller.evaluate` is a pure function of ``(now, inputs)`` plus
the controller's own hysteresis state):

* ``mem_pressure`` — the worst rank's ``nbytes / max_malloc_per_server``
  crossed ``control_scaleout_pressure`` → **scale_out** (hot rank
  named).
* ``slo_firing`` — a page-severity alert is FIRING while jobs hold
  backlog → **scale_out**.
* ``tenant_hog`` — memory is hot AND one unthrottled non-default tenant
  holds more than half the fleet's queued bytes → **throttle** it (cap
  its quota at ~its current footprint; the put path answers
  ``ADLB_BACKOFF`` beyond that). The pre-throttle quota is remembered;
  when pressure recedes below ``control_scalein_pressure`` the tenant is
  **unthrottled** (quota restored, -1 encodes unlimited).
* ``fleet_idle`` — every rank's pressure is below
  ``control_scalein_pressure``, nothing is firing, no job holds
  backlog, and the fleet is above both ``control_min_servers`` and the
  drain-safety floor of 2 → **scale_in** (newest shard drains through
  the zero-loss promote path).

Threading: ``evaluate``/``update_policy`` run on the master's reactor
thread only; the ops HTTP thread reads ``history`` / ``status_pub`` /
``policy_doc()``, which are swapped or append-only (``safe_copy`` on the
reading side), the same discipline as the SLO engine's published views.

An unconfigured world (``control=False``, the default) constructs no
Controller, starts no extra work on the tick, and mints no metrics —
frame-identical to a pre-controller build.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

# decision outcomes (append-only vocabulary, like the SLO alert states)
ACT = "act"  # returned to the reactor, which enacts and
# rewrites to "enacted" / "error"
DRY_RUN = "dry_run"
HELD = "held"  # epoch-churn hold window open
COOLDOWN = "cooldown"  # this action's key acted too recently
BOUNDED = "bounded"  # min/max server rail refused it

# mutable-policy keys POST /control may touch (everything else 400s)
_POLICY_KEYS = (
    "dry_run", "min_servers", "max_servers", "cooldown_s",
    "scaleout_pressure", "scalein_pressure", "throttle_frac",
)


def parse_policy(doc: dict, base: Optional[dict] = None) -> dict:
    """Validate + normalize a policy dict (Config knobs at construction
    and POST /control bodies go through the same gate). Raises
    ValueError with an operator-readable message — the ops route
    answers 400."""
    if not isinstance(doc, dict):
        raise ValueError(f"policy must be a dict, got {type(doc).__name__}")
    unknown = set(doc) - set(_POLICY_KEYS)
    if unknown:
        raise ValueError(f"unknown policy keys {sorted(unknown)}")
    pol = dict(base or {})
    for k in _POLICY_KEYS:
        if k in doc:
            pol[k] = doc[k]
    pol["dry_run"] = bool(pol.get("dry_run", False))
    pol["min_servers"] = int(pol.get("min_servers", 1))
    pol["max_servers"] = int(pol.get("max_servers", 0))
    pol["cooldown_s"] = float(pol.get("cooldown_s", 10.0))
    pol["scaleout_pressure"] = float(pol.get("scaleout_pressure", 0.85))
    pol["scalein_pressure"] = float(pol.get("scalein_pressure", 0.30))
    pol["throttle_frac"] = float(pol.get("throttle_frac", 0.5))
    if pol["min_servers"] < 1:
        raise ValueError("min_servers must be >= 1")
    if pol["max_servers"] < 0:
        raise ValueError("max_servers must be >= 0")
    if pol["max_servers"] and pol["max_servers"] < pol["min_servers"]:
        raise ValueError("max_servers, when bounded, must be >= "
                         "min_servers")
    if pol["cooldown_s"] < 0:
        raise ValueError("cooldown_s must be >= 0")
    if not (0.0 < pol["scaleout_pressure"] <= 1.0):
        raise ValueError("scaleout_pressure must be in (0, 1]")
    if not (0.0 <= pol["scalein_pressure"] < pol["scaleout_pressure"]):
        raise ValueError(
            "scalein_pressure must be in [0, scaleout_pressure)")
    if not (0.0 < pol["throttle_frac"] <= 1.0):
        raise ValueError("throttle_frac must be in (0, 1]")
    return pol


class Controller:
    """Master-side decision engine. One instance per master server,
    created at init when ``Config(control=True)``."""

    def __init__(self, policy: dict, eval_interval: float = 1.0,
                 now: Optional[float] = None) -> None:
        self.policy = parse_policy(policy)
        self.eval_interval = max(eval_interval, 1e-3)
        self.started_at = time.monotonic() if now is None else now
        self.actions_total = 0  # enacted only (dry-run stays 0)
        self.history: deque = deque(maxlen=256)
        self.status_pub: dict = {}
        # hysteresis state
        self._cooldowns: dict[str, float] = {}  # key -> until
        self._epoch: Optional[int] = None
        self._hold_until = 0.0
        # throttled tenants: jid -> pre-throttle quota_bytes (0 meant
        # unlimited; the restore encodes it as -1 on the update op)
        self._throttled: dict[int, int] = {}
        # last recorded (rule -> outcome): suppresses the repeat spam of
        # a rule stuck in the same suppressed outcome every tick
        self._last_outcome: dict[str, str] = {}

    @property
    def dry_run(self) -> bool:
        return bool(self.policy["dry_run"])

    # -- policy --------------------------------------------------------------

    def policy_doc(self) -> dict:
        return dict(self.policy)

    def update_policy(self, doc: dict) -> dict:
        """POST /control: merge a validated tweak into the live policy.
        Swap-published (a fresh dict) so HTTP readers never see a
        half-applied update."""
        self.policy = parse_policy(doc, base=self.policy)
        return dict(self.policy)

    # -- churn hysteresis ----------------------------------------------------

    def note_epoch(self, epoch: int, now: float) -> None:
        """Membership change: freeze actions for a grace period — the
        SLO engine's hold, applied to actuators instead of alert state.
        An enacted scale-out's own join bumps the epoch, so the
        controller self-holds while the new shard warms up."""
        if self._epoch is not None and epoch != self._epoch:
            self._hold_until = now + max(4.0 * self.eval_interval, 2.0)
        self._epoch = epoch

    # -- decisions -----------------------------------------------------------

    @staticmethod
    def _cooldown_key(action: dict) -> str:
        kind = action["kind"]
        if kind in ("scale_out", "scale_in"):
            return "scale"  # shared: never bounce a shard out-then-in
        if kind in ("throttle", "unthrottle"):
            return f"throttle:{action.get('job')}"
        return kind

    def _decide(self, now: float, rule: str, inputs: dict, action: dict,
                held: bool, bound: Optional[str] = None) -> dict:
        key = self._cooldown_key(action)
        if held:
            outcome = HELD
        elif bound is not None:
            outcome = BOUNDED
        elif now < self._cooldowns.get(key, 0.0):
            outcome = COOLDOWN
        else:
            # stamp the cooldown for dry-run too: the decision stream
            # must pace exactly like a live controller would
            self._cooldowns[key] = now + self.policy["cooldown_s"]
            outcome = DRY_RUN if self.dry_run else ACT
        d = {
            "at": round(now, 3),
            "rule": rule,
            "inputs": inputs,
            "action": action,
            "outcome": outcome,
        }
        if bound is not None:
            d["bound"] = bound
        return d

    def evaluate(self, now: float, inputs: dict) -> list[dict]:
        """One tick: run the rules over ``inputs`` and return the
        decision records that are new this tick (a rule stuck in the
        same suppressed outcome is recorded once, not every tick).
        Records with outcome ``act`` are the caller's to enact — it
        rewrites their outcome to ``enacted``/``error`` in place (the
        history holds the same dicts).

        ``inputs`` (all optional, zero-defaults):
        ``live_servers`` int; ``pressure`` {rank: frac-of-cap};
        ``firing`` int (page-severity FIRING alerts);
        ``jobs`` {jid: {"depth", "bytes", "oldest_age_s", "backoffs",
        "quota_bytes", "state"}}; ``backoffs`` int (fleet total);
        ``oldest_lease_s`` float; ``epoch`` int.
        """
        if inputs.get("epoch") is not None:
            self.note_epoch(int(inputs["epoch"]), now)
        held = now < self._hold_until
        pol = self.policy
        live = int(inputs.get("live_servers", 0) or 0)
        pressure: dict = inputs.get("pressure") or {}
        worst = max(pressure.values(), default=0.0)
        jobs: dict = inputs.get("jobs") or {}
        backlog = sum(int(j.get("depth", 0) or 0) for j in jobs.values())
        firing = int(inputs.get("firing", 0) or 0)
        decisions: list[dict] = []

        def hot_rank() -> Optional[int]:
            return max(pressure, key=pressure.get) if pressure else None

        # ---- scale_out: mem_pressure, then slo_firing
        if worst >= pol["scaleout_pressure"]:
            decisions.append(self._decide(
                now, "mem_pressure",
                {"worst_pressure": round(worst, 4),
                 "threshold": pol["scaleout_pressure"],
                 "live_servers": live},
                {"kind": "scale_out", "hot_rank": hot_rank()},
                held=held,
                bound="max_servers" if pol["max_servers"]
                and live >= pol["max_servers"] else None,
            ))
        elif firing > 0 and backlog > 0:
            decisions.append(self._decide(
                now, "slo_firing",
                {"firing": firing, "backlog": backlog,
                 "live_servers": live},
                {"kind": "scale_out", "hot_rank": hot_rank()},
                held=held,
                bound="max_servers" if pol["max_servers"]
                and live >= pol["max_servers"] else None,
            ))

        # ---- tenant throttling: hog under pressure; release when calm
        total_bytes = sum(
            int(j.get("bytes", 0) or 0) for j in jobs.values())
        if worst >= pol["scaleout_pressure"] and total_bytes > 0:
            for jid, j in sorted(jobs.items()):
                jb = int(j.get("bytes", 0) or 0)
                if (
                    jid != 0
                    and jid not in self._throttled
                    and j.get("state", "running") == "running"
                    and not int(j.get("quota_bytes", 0) or 0)
                    and jb > pol["throttle_frac"] * total_bytes
                ):
                    # cap the hog near its current footprint: it keeps
                    # what it queued, the put path backpressures growth
                    quota = max(jb, 1)
                    d = self._decide(
                        now, "tenant_hog",
                        {"job": jid, "job_bytes": jb,
                         "total_bytes": total_bytes,
                         "worst_pressure": round(worst, 4)},
                        {"kind": "throttle", "job": jid,
                         "quota_bytes": quota},
                        held=held,
                    )
                    if d["outcome"] in (ACT, DRY_RUN):
                        self._throttled[jid] = int(
                            j.get("quota_bytes", 0) or 0)
                    decisions.append(d)
                    break  # one tenant per tick
        elif self._throttled and worst <= pol["scalein_pressure"]:
            jid = sorted(self._throttled)[0]
            prev = self._throttled[jid]
            d = self._decide(
                now, "pressure_recovered",
                {"job": jid, "worst_pressure": round(worst, 4),
                 "restore_quota": prev},
                {"kind": "unthrottle", "job": jid,
                 # -1 = restore unlimited (the jobs.apply update op's
                 # encoding; 0 would mean "leave unchanged")
                 "quota_bytes": prev if prev else -1},
                held=held,
            )
            if d["outcome"] in (ACT, DRY_RUN):
                self._throttled.pop(jid, None)
            decisions.append(d)

        # ---- scale_in: fleet idle, above the floor
        if (
            not decisions
            and worst <= pol["scalein_pressure"]
            and firing == 0
            and backlog == 0
            and live > max(pol["min_servers"], 2)
        ):
            decisions.append(self._decide(
                now, "fleet_idle",
                {"worst_pressure": round(worst, 4),
                 "threshold": pol["scalein_pressure"],
                 "live_servers": live,
                 "min_servers": pol["min_servers"]},
                {"kind": "scale_in"},
                held=held,
            ))

        # ---- record: actions always; suppressed outcomes only when
        # they CHANGE (a held/cooldown rule re-evaluated every tick must
        # not fill the history with identical rows)
        out: list[dict] = []
        seen_rules = set()
        for d in decisions:
            seen_rules.add(d["rule"])
            if d["outcome"] in (ACT, DRY_RUN) or \
                    self._last_outcome.get(d["rule"]) != d["outcome"]:
                self._last_outcome[d["rule"]] = d["outcome"]
                self.history.append(d)
                out.append(d)
        for rule in list(self._last_outcome):
            if rule not in seen_rules:
                del self._last_outcome[rule]
        return out

    # -- published status ----------------------------------------------------

    def publish(self, now: float, inputs: dict) -> None:
        """Swap the compact status doc the HTTP thread reads."""
        self.status_pub = {
            "at": round(now, 3),
            "held": now < self._hold_until,
            "hold_until": round(self._hold_until, 3),
            "cooldowns": {
                k: round(u - now, 3)
                for k, u in self._cooldowns.items() if u > now
            },
            "throttled": {
                str(j): q for j, q in sorted(self._throttled.items())
            },
            "live_servers": int(inputs.get("live_servers", 0) or 0),
            "worst_pressure": round(
                max((inputs.get("pressure") or {}).values(),
                    default=0.0), 4),
            "firing": int(inputs.get("firing", 0) or 0),
            "backoffs": int(inputs.get("backoffs", 0) or 0),
            "oldest_lease_s": round(
                float(inputs.get("oldest_lease_s", 0.0) or 0.0), 3),
        }
