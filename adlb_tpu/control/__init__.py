"""The fleet brain (PR 19): closed-loop control over the actuators.

See :mod:`adlb_tpu.control.controller` for the decision engine the
master's obs tick drives when ``Config(control=True)``.
"""

from adlb_tpu.control.controller import Controller, parse_policy

__all__ = ["Controller", "parse_policy"]
